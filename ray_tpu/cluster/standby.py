"""Warm-standby head: snapshot bootstrap + live WAL replay + promotion.

A :class:`StandbyHead` tails the leader's persistence stream (one
``StandbyHello`` bootstrap, then pushed ``ReplWal`` batches from the
leader's :class:`~ray_tpu.cluster.replication.ReplicationHub`) and
continuously replays it into fully-built, snapshot-shaped head tables —
owner-sharded exactly like the leader's, applied per shard group
(conflict-free: records for different shards commute). Promotion is
therefore an epoch bump + listener bind: the merged tables hand off
in-memory to a fresh :class:`~ray_tpu.cluster.head.HeadServer`
(``HandoffPersistence``) on the dead leader's port; no disk replay.

Leader election needs no external coordinator: the standby runs the same
strike-based health shape agents use (``head_miss_threshold`` strikes of
``head_health_timeout_s / threshold`` windows, shipped batches counting
as liveness), declares the leader dead, and promotes. Split-brain is
impossible by construction — the promoted head's epoch is strictly
higher, every mutating RPC is epoch-stamped, and a deposed leader that
was merely partitioned fences itself the moment it observes the higher
epoch (from its own shipping stream's ``{"fenced"}`` replies, or from
any request stamped with the newer epoch).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.config import cfg

from .common import new_id
from .replication import FAILOVER_MS
from .rpc import RpcClient, RpcError, RpcNotLeaderError, RpcServer
from .shards import ShardedTable, group_records_by_shard

logger = logging.getLogger("ray_tpu.cluster.standby")

# WAL record kind -> the sharded-table key it mutates (None = applies to
# an unsharded table and must replay in stream order). The ONE map both
# the shard-group replay and the routing-equivalence test use.
_SHARDED_KINDS = {
    "task_lease": lambda rec: rec[1]["lease_id"],
    "task_lease_gone": lambda rec: rec[1],
    "peer_link": lambda rec: rec[1]["link_id"],
    "peer_link_gone": lambda rec: rec[1],
    "serve_stream": lambda rec: rec[1]["stream_id"],
    "serve_stream_ckpt": lambda rec: rec[1]["stream_id"],
    "serve_stream_gone": lambda rec: rec[1],
}


def record_shard_key(rec: tuple) -> Optional[str]:
    fn = _SHARDED_KINDS.get(rec[0])
    try:
        return fn(rec) if fn is not None else None
    except (KeyError, IndexError, TypeError):
        return None


class StandbyHead:
    """One warm standby following one leader."""

    def __init__(
        self,
        leader_address: str,
        host: str = "127.0.0.1",
        port: int = 0,
        persist_path: Optional[str] = None,
        standby_id: Optional[str] = None,
        auto_promote: bool = True,
        use_device_scheduler: Optional[bool] = None,
    ):
        self.leader_address = leader_address
        self.persist_path = persist_path
        self.standby_id = standby_id or f"sb-{new_id()}"
        self.auto_promote = auto_promote
        self.use_device_scheduler = use_device_scheduler
        self.role = "standby"
        self.promoted: Optional[Any] = None  # the HeadServer once leader
        self.on_promoted = None  # callback(head) after a promotion
        self.leader_epoch = 0
        self.applied_seq = 0
        self._expected = 1
        self._lock = threading.RLock()
        self._shutdown = False
        self._leader_seen = time.monotonic()
        self._last_batch = time.monotonic()  # ship-stream silence clock
        n = max(1, int(cfg.head_shards))
        self._num_shards = n
        # snapshot-shaped mirror tables (the leader's _snapshot_state
        # layout), continuously replayed; lease tables owner-sharded
        self._kv: Dict[str, bytes] = {}
        self._named_actors: Dict[str, str] = {}
        self._actors: Dict[str, dict] = {}
        self._actor_specs: Dict[str, Any] = {}
        self._leases: Dict[str, Any] = {}
        self._jobs: list = []
        self._streams: Dict[str, dict] = {}
        self._stream_tombstones: list = []
        self._stream_inline: Dict[str, tuple] = {}
        self._task_leases: ShardedTable = ShardedTable(n)
        self._peer_links: ShardedTable = ShardedTable(n)
        self._pending_revokes: Dict[str, dict] = {}
        self._serve_fleets: Dict[str, dict] = {}
        self._weights_epochs: Dict[str, dict] = {}
        self._serve_streams: ShardedTable = ShardedTable(n)
        self.metrics = {
            "wal_applied": 0,
            "snapshots_installed": 0,
            "resyncs_requested": 0,
            "batches_received": 0,
        }
        self._server = RpcServer(
            {
                "ReplWal": self._h_repl_wal,
                "HeadRole": self._h_head_role,
                "QueryState": self._h_query_state,
                "Ping": lambda r: "pong",
            },
            host=host,
            port=port,
        )
        self.address = self._server.address
        try:
            self._hello()
        except Exception:
            self._server.stop()
            raise
        self._watch_thread = threading.Thread(
            target=self._watch_loop, name="standby-watch", daemon=True
        )
        self._watch_thread.start()

    # -- bootstrap -------------------------------------------------------
    def _hello(self) -> None:
        client = RpcClient(self.leader_address)
        try:
            reply = client.call(
                "StandbyHello",
                {"standby_id": self.standby_id, "address": self.address},
                timeout=30.0,
                retries=3,
                retry_interval=0.2,
            )
        finally:
            client.close()
        with self._lock:
            self._install_snapshot(reply["snapshot"])
            self.applied_seq = int(reply["from_seq"])
            self._expected = self.applied_seq + 1
            self.leader_epoch = int(reply.get("epoch", 0))
            self._leader_seen = time.monotonic()
            self._last_batch = time.monotonic()
        logger.info(
            "standby %s bootstrapped from %s (seq %d, epoch %d)",
            self.standby_id[:8],
            self.leader_address,
            self.applied_seq,
            self.leader_epoch,
        )

    def _install_snapshot(self, snap: dict) -> None:
        """Reset every mirror table from a leader snapshot (bootstrap,
        seq'd barrier, or gap re-sync). Caller holds self._lock."""
        self._kv = dict(snap.get("kv", {}))
        self._named_actors = dict(snap.get("named_actors", {}))
        self._actors = {
            aid: dict(fields)
            for aid, fields in snap.get("actors", {}).items()
        }
        self._actor_specs = dict(snap.get("actor_specs", {}))
        self._leases = dict(snap.get("leases", {}))
        self._jobs = list(snap.get("jobs", []))
        self._streams = {
            tid: dict(st) for tid, st in snap.get("streams", {}).items()
        }
        self._stream_tombstones = list(snap.get("stream_tombstones", []))
        self._stream_inline = dict(snap.get("stream_inline", {}))
        self._task_leases = ShardedTable(self._num_shards)
        for row in snap.get("task_leases", []):
            self._task_leases[row["lease_id"]] = dict(row)
        self._peer_links = ShardedTable(self._num_shards)
        for row in snap.get("peer_links", []):
            self._peer_links[row["link_id"]] = dict(row)
        self._pending_revokes = {
            rid: dict(row)
            for rid, row in snap.get("pending_revokes", {}).items()
        }
        self._serve_fleets = {
            dep: dict(f) for dep, f in snap.get("serve_fleets", {}).items()
        }
        self._weights_epochs = {
            dep: {
                "committed": int(w.get("committed", 0)),
                "meta": dict(w.get("meta", {})),
                "sealed": dict(w["sealed"]) if w.get("sealed") else None,
            }
            for dep, w in snap.get("weights_epochs", {}).items()
        }
        self._serve_streams = ShardedTable(self._num_shards)
        for row in snap.get("serve_streams", []):
            self._serve_streams[row["stream_id"]] = dict(row)
        if "epoch" in snap:
            self.leader_epoch = max(
                self.leader_epoch, int(snap.get("epoch", 0))
            )
        self.metrics["snapshots_installed"] += 1

    # -- live replay -----------------------------------------------------
    def _h_repl_wal(self, batch) -> dict:
        with self._lock:
            if self.promoted is not None:
                # promoted: fence the deposed leader off its own
                # shipping stream
                return {
                    "fenced": self.promoted.cluster_epoch,
                    "leader": self.promoted.address,
                }
            if self.role != "standby":
                # promotion IN FLIGHT — and it may yet abort (the bind
                # interlock exists precisely for the leader-was-alive
                # false positive). Fencing here would depose a live
                # leader that can never be replaced (it holds the port).
                # Neither fence nor apply: leave the records pending;
                # the shipper re-sends them and this standby either
                # resumes (abort) or starts fencing (promoted).
                return {"applied_to": self.applied_seq}
            epoch = int(batch.epoch)
            if epoch < self.leader_epoch:
                # a deposed leader still shipping: refuse (and tell it)
                return {"fenced": self.leader_epoch, "leader": ""}
            self.leader_epoch = max(self.leader_epoch, epoch)
            self._leader_seen = time.monotonic()
            self._last_batch = time.monotonic()
            self.metrics["batches_received"] += 1
            if batch.snapshot is not None:
                # gap re-sync: full reset at snap_seq, tail ships after
                self._install_snapshot(batch.snapshot)
                self.applied_seq = int(batch.snap_seq)
                self._expected = self.applied_seq + 1
            records = batch.records or []
            start = int(batch.start_seq)
            if records:
                if start > self._expected:
                    # a batch went missing (dropped send, ring eviction
                    # upstream): ask the leader to rewind / re-sync
                    self.metrics["resyncs_requested"] += 1
                    return {"resync_from": self._expected}
                fresh = [
                    (s, item)
                    for s, item in zip(
                        range(start, start + len(records)), records
                    )
                    if s >= self._expected
                ]
                self._apply_items([item for _, item in fresh])
                if fresh:
                    self.applied_seq = fresh[-1][0]
                    self._expected = self.applied_seq + 1
            return {"applied_to": self.applied_seq}

    def _apply_items(self, items: List[tuple]) -> None:
        """Apply a contiguous run of stream items. Runs of consecutive
        WAL records apply as shard groups (the owner-sharded replay:
        per-shard order preserved, cross-shard records commute); snapshot
        barriers reset everything and cut the stream at their position.
        Caller holds self._lock."""
        run: List[tuple] = []
        for kind, payload in items:
            if kind == "snap":
                self._apply_wal_run(run)
                run = []
                self._install_snapshot(payload)
            else:
                run.append(payload)
        self._apply_wal_run(run)

    def _apply_wal_run(self, records: List[tuple]) -> None:
        if not records:
            return
        groups, residue = group_records_by_shard(
            records, record_shard_key, self._num_shards
        )
        for shard in sorted(groups):
            for rec in groups[shard]:
                self._apply_record(rec)
        for rec in residue:
            self._apply_record(rec)
        self.metrics["wal_applied"] += len(records)

    def _apply_record(self, rec: tuple) -> None:
        """One WAL record into the snapshot-shaped mirrors. Kinds match
        head._load_persisted's replay switch; unknown kinds are ignored
        (forward compatibility — a newer leader may ship records an
        older standby build cannot interpret, and losing them is exactly
        what the next snapshot barrier repairs)."""
        kind = rec[0]
        if kind == "kv_put":
            self._kv[rec[1]] = rec[2]
        elif kind == "kv_del":
            self._kv.pop(rec[1], None)
        elif kind == "actor":
            fields, spec, name = rec[1], rec[2], rec[3]
            self._actors[fields["actor_id"]] = dict(fields)
            if spec is not None:
                self._actor_specs[fields["actor_id"]] = spec
            if name:
                self._named_actors[name] = fields["actor_id"]
        elif kind == "actor_dead":
            info = self._actors.get(rec[1])
            if info is not None:
                info["state"] = "DEAD"
                name = info.get("name")
                if name and self._named_actors.get(name) == rec[1]:
                    del self._named_actors[name]
        elif kind == "task_lease":
            self._task_leases[rec[1]["lease_id"]] = dict(rec[1])
        elif kind == "task_lease_gone":
            self._task_leases.pop(rec[1], None)
        elif kind == "peer_link":
            self._peer_links[rec[1]["link_id"]] = dict(rec[1])
        elif kind == "peer_link_gone":
            self._peer_links.pop(rec[1], None)
        elif kind == "revoke_pending":
            self._pending_revokes[rec[1]["revoke_id"]] = dict(rec[1])
        elif kind == "revoke_done":
            self._pending_revokes.pop(rec[1], None)
        elif kind == "serve_fleet":
            row = rec[1]
            self._serve_fleets[row["deployment"]] = {
                "epoch": int(row.get("epoch", 0)),
                "members": list(row.get("members", ())),
            }
        elif kind == "serve_stream":
            self._serve_streams[rec[1]["stream_id"]] = dict(rec[1])
        elif kind == "serve_stream_ckpt":
            row = self._serve_streams.get(rec[1]["stream_id"])
            if row is not None:
                row["delivered"] = max(
                    int(row.get("delivered", 0)),
                    int(rec[1].get("delivered", 0)),
                )
                if rec[1].get("router_id"):
                    row["router_id"] = rec[1]["router_id"]
        elif kind == "serve_stream_gone":
            self._serve_streams.pop(rec[1], None)
        elif kind == "weights_epoch":
            # two-phase publish fence: mirror seal/commit so a promoted
            # standby exposes exactly the old or the new epoch — the
            # sealed-but-uncommitted phase survives but never reads as
            # committed (the publisher's retry re-seals + commits)
            row = rec[1]
            w = self._weights_epochs.setdefault(
                row["deployment"],
                {"committed": 0, "meta": {}, "sealed": None},
            )
            if row.get("phase") == "seal":
                w["sealed"] = {
                    "epoch": int(row["epoch"]),
                    "meta": dict(row.get("meta", {})),
                }
            else:
                w["committed"] = int(row["epoch"])
                w["meta"] = dict(row.get("meta", {}))
                w["sealed"] = None

    # -- promotion -------------------------------------------------------
    def tables_snapshot(self) -> dict:
        """The mirror tables in the leader's exact snapshot shape —
        what promotion hands the new HeadServer, and what the
        convergence test compares against the leader's
        _snapshot_state()."""
        with self._lock:
            return {
                "epoch": self.leader_epoch,
                "kv": dict(self._kv),
                "named_actors": dict(self._named_actors),
                "actors": {
                    aid: dict(f) for aid, f in self._actors.items()
                },
                "actor_specs": dict(self._actor_specs),
                "jobs": list(self._jobs),
                "leases": dict(self._leases),
                "task_leases": [
                    dict(r) for r in self._task_leases.values()
                ],
                "peer_links": [
                    dict(r) for r in self._peer_links.values()
                ],
                "streams": {
                    tid: dict(st) for tid, st in self._streams.items()
                },
                "stream_tombstones": list(self._stream_tombstones),
                "stream_inline": dict(self._stream_inline),
                "pending_revokes": {
                    rid: dict(r)
                    for rid, r in self._pending_revokes.items()
                },
                "serve_fleets": {
                    dep: dict(f)
                    for dep, f in self._serve_fleets.items()
                },
                "weights_epochs": {
                    dep: dict(w)
                    for dep, w in self._weights_epochs.items()
                },
                "serve_streams": [
                    dict(r) for r in self._serve_streams.values()
                ],
            }

    def promote(
        self,
        port: Optional[int] = None,
        host: str = "127.0.0.1",
        bind_timeout_s: float = 10.0,
    ):
        """Fenced promotion: epoch bump + listener bind. Binds the dead
        leader's port by default (agents/clients reconnect untouched —
        their next stamped RPC is fenced stale and they re-register,
        exactly the restart resync protocol). On one host the bind
        doubles as a leadership interlock: a leader that is actually
        alive still holds its port and the promotion aborts."""
        with self._lock:
            if self.promoted is not None:
                return self.promoted
            if self.role == "promoting":
                raise RuntimeError("promotion already in flight")
            self.role = "promoting"
        t0 = time.monotonic()
        try:
            head = self._promote_inner(port, host, bind_timeout_s)
        except BaseException:
            # ANY failure (bind interlock, handoff backend I/O, ...)
            # returns this standby to following — a wedged "promoting"
            # role would block every later attempt
            with self._lock:
                if self.promoted is None:
                    self.role = "standby"
            raise
        elapsed_ms = (time.monotonic() - t0) * 1e3
        FAILOVER_MS.observe(elapsed_ms)
        with self._lock:
            self.promoted = head
            self.role = "leader"
        return self._finish_promote(head, elapsed_ms)

    def _promote_inner(self, port, host, bind_timeout_s):
        snap = self.tables_snapshot()
        from .head import HeadServer
        from .persistence import (
            FilePersistence,
            HandoffPersistence,
            MemPersistence,
        )

        inner = (
            FilePersistence(self.persist_path)
            if self.persist_path
            else MemPersistence()
        )
        backend = HandoffPersistence(inner, snap)
        if port is None:
            port = int(self.leader_address.rsplit(":", 1)[1])
        deadline = time.monotonic() + bind_timeout_s
        while True:
            try:
                return HeadServer(
                    host=host,
                    port=port,
                    use_device_scheduler=self.use_device_scheduler,
                    persist_path=self.persist_path,
                    persist_backend=backend,
                )
            except RpcError:
                # port still held (late TIME_WAIT, or the leader is in
                # fact alive): retry briefly, then abort the promotion.
                # Each retry re-loads the SAME handoff snapshot —
                # HandoffPersistence.load() is not consumed on read.
                if time.monotonic() >= deadline:
                    logger.warning(
                        "promotion aborted: could not bind %s:%d "
                        "(leader still alive?)",
                        host,
                        port,
                    )
                    raise
                time.sleep(0.05)

    def _finish_promote(self, head, elapsed_ms: float):
        logger.warning(
            "standby %s promoted to leader at %s (epoch %d -> %d, "
            "%.0f ms)",
            self.standby_id[:8],
            head.address,
            self.leader_epoch,
            head.cluster_epoch,
            elapsed_ms,
        )
        try:
            # failover is a post-mortem moment (ISSUE 15): the promoted
            # head snapshots a flight-recorder bundle of what it
            # inherited, and the promotion lands as a trace span
            from ray_tpu.util.tracing import SPANS

            SPANS.record(
                "head_failover",
                "control",
                time.time() - elapsed_ms / 1e3,
                elapsed_ms / 1e3,
                pid="head",
                from_epoch=self.leader_epoch,
                to_epoch=head.cluster_epoch,
            )
            head._dump_crash_bundle(
                f"head-failover-epoch{head.cluster_epoch}"
            )
        except Exception:  # noqa: BLE001 - observability only
            logger.debug("failover bundle failed", exc_info=True)
        cb = self.on_promoted
        if cb is not None:
            try:
                cb(head)
            except Exception:  # noqa: BLE001 - observer only
                logger.exception("on_promoted callback failed")
        return head

    # -- leader election (strike-based, agents' health shape) -----------
    def _watch_loop(self) -> None:
        strikes = 0
        client = RpcClient(self.leader_address)
        try:
            while not self._shutdown:
                threshold = max(1, int(cfg.head_miss_threshold))
                window = max(
                    0.05, float(cfg.head_health_timeout_s) / threshold
                )
                time.sleep(window)
                with self._lock:
                    if self._shutdown or self.role != "standby":
                        return
                    seen_gap = time.monotonic() - self._leader_seen
                if seen_gap < window:
                    # shipped batches ARE liveness: no probe needed
                    strikes = 0
                    continue
                try:
                    client.call("Ping", timeout=max(0.2, window))
                    strikes = 0
                    self._leader_seen = time.monotonic()
                    # leader alive but silent on the ship stream (its
                    # keepalives stopped): it dropped us during an
                    # outage on OUR side — re-hello to re-register and
                    # re-bootstrap (resync, not an error)
                    if (
                        time.monotonic() - self._last_batch
                        > max(3.0, 5.0 * window)
                    ):
                        try:
                            self._hello()
                        except Exception:  # noqa: BLE001 - retried next tick
                            logger.debug(
                                "standby re-hello failed", exc_info=True
                            )
                except RpcNotLeaderError:
                    # the leader fenced itself (someone else promoted):
                    # this standby is stale — keep following; a re-hello
                    # against the hint would be the HA-pair extension
                    strikes = 0
                except (RpcError, Exception):  # noqa: BLE001
                    strikes += 1
                if strikes >= threshold:
                    logger.warning(
                        "standby %s: leader %s missed %d consecutive "
                        "probe windows; declaring it dead",
                        self.standby_id[:8],
                        self.leader_address,
                        threshold,
                    )
                    if not self.auto_promote:
                        return
                    try:
                        self.promote()
                    except Exception:  # noqa: BLE001
                        # bind interlock (leader alive after all), disk
                        # error building the handoff backend, a racing
                        # manual promote — whatever it was, the watch
                        # must SURVIVE it: resume following with a clean
                        # slate and try again on the next strike-out,
                        # never die silently leaving the cluster
                        # leaderless
                        logger.exception(
                            "standby %s promotion attempt failed; "
                            "resuming watch",
                            self.standby_id[:8],
                        )
                        with self._lock:
                            if self.promoted is None:
                                self.role = "standby"
                        strikes = 0
                        continue
                    return
        finally:
            client.close()

    # -- RPC surface -----------------------------------------------------
    def _h_head_role(self, req) -> dict:
        with self._lock:
            head = self.promoted
            return {
                "role": "leader" if head is not None else self.role,
                "standby_id": self.standby_id,
                "epoch": (
                    head.cluster_epoch
                    if head is not None
                    else self.leader_epoch
                ),
                "leader_hint": (
                    head.address if head is not None else ""
                ),
            }

    def _h_query_state(self, req) -> dict:
        with self._lock:
            return {
                "role": self.role,
                "standby_id": self.standby_id,
                "leader": self.leader_address,
                "leader_epoch": self.leader_epoch,
                "applied_seq": self.applied_seq,
                "metrics": dict(self.metrics),
                "shards": {
                    "task_leases": self._task_leases.shard_sizes(),
                    "peer_links": self._peer_links.shard_sizes(),
                },
                "tables": {
                    "kv": len(self._kv),
                    "actors": len(self._actors),
                    "leases": len(self._leases),
                    "task_leases": len(self._task_leases),
                    "peer_links": len(self._peer_links),
                    "pending_revokes": len(self._pending_revokes),
                    "serve_fleets": len(self._serve_fleets),
                    "weights_epochs": len(self._weights_epochs),
                    "serve_streams": len(self._serve_streams),
                },
            }

    def shutdown(self) -> None:
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
        self._server.stop()

    def wait_promoted(self, timeout: float = 30.0):
        """Block until this standby's auto-promotion completed; returns
        the promoted HeadServer (or None on timeout)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self.promoted is not None:
                    return self.promoted
            time.sleep(0.05)
        return None
