"""Distributed multi-process runtime: head (GCS analog), node agents
(raylet analog), worker subprocesses, and the driver client — over gRPC.

Lazy exports keep worker-subprocess startup light (head/agent pull in the
scheduler kernels; workers only need rpc + common).
"""
from typing import Any

_EXPORTS = {
    "RemoteRuntime": ("ray_tpu.cluster.client", "RemoteRuntime"),
    "connect": ("ray_tpu.cluster.client", "connect"),
    "Cluster": ("ray_tpu.cluster.cluster_utils", "Cluster"),
    "HeadServer": ("ray_tpu.cluster.head", "HeadServer"),
    "NodeAgent": ("ray_tpu.cluster.agent", "NodeAgent"),
    "LeaseRequest": ("ray_tpu.cluster.common", "LeaseRequest"),
    "NodeInfo": ("ray_tpu.cluster.common", "NodeInfo"),
    "JobSubmissionClient": ("ray_tpu.cluster.jobs", "JobSubmissionClient"),
    "RpcClient": ("ray_tpu.cluster.rpc", "RpcClient"),
    "RpcServer": ("ray_tpu.cluster.rpc", "RpcServer"),
    "RpcError": ("ray_tpu.cluster.rpc", "RpcError"),
}

__all__ = list(_EXPORTS)


def __getattr__(name: str) -> Any:
    try:
        module_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    return getattr(importlib.import_module(module_name), attr)
