"""Worker process: executes leases pushed by its node agent.

The analog of the reference's worker process embedding a CoreWorker
(/root/reference/src/ray/core_worker/): receives ``PushTask`` RPCs
(task_execution/task_receiver.h:43), resolves ObjectRef arguments
(DependencyResolver), runs user code, and seals results — small values
inline (max_direct_call_object_size, ray_config_def.h:218), large ones
into the node's shared-memory arena (plasma Put). Actor instances live
in-process for the worker's lifetime; pushes are serialized per worker,
giving actor-method ordering.

Kept import-light: jax and the rest of ray_tpu load lazily (user code
triggers them), so a pool of workers forks in well under a second.
"""
from __future__ import annotations

import argparse
import importlib
import logging
import os
import pickle
import sys
import threading
import time
import traceback
from collections import deque

_STREAM_END = object()  # generator-exhausted sentinel (values can be None)
from typing import Any, Dict, List, Optional

import cloudpickle

from . import serialization as wire
from .common import (
    DISPATCH_OVERHEAD_US,
    INLINE_OBJECT_MAX,
    SealInfo,
    dispatch_sampled,
)
from .object_plane import OBJECT_TRANSFER_BYTES, SHM_HITS, SHM_MISSES
from .rpc import RpcClient, RpcError, RpcServer

logger = logging.getLogger("ray_tpu.cluster.worker")


async def _invoke_maybe_async(instance, method: str, args, kwargs, sems,
                              trace=None):
    """Run one actor method on the actor's event loop; awaits coroutine
    methods, runs sync methods inline (briefly blocking the loop — the
    reference's asyncio-actor semantics for def methods). ``sems`` maps
    concurrency-group name -> asyncio.Semaphore bounding in-flight starts.
    ``trace`` is installed around the call so nested submissions from the
    method inherit the caller's trace id (the coroutine runs in its own
    contextvars context, so per-task installation is race-free)."""
    import inspect

    fn = getattr(instance, method)
    opts = getattr(fn, "_ray_tpu_method_options", None) or {}
    group = opts.get("concurrency_group", "_default")
    sem = sems.get(group) or sems["_default"]
    async with sem:
        token = None
        if trace is not None:
            from ray_tpu.util import tracing

            token = tracing.install(trace)
        try:
            out = fn(*args, **kwargs)
            from ray_tpu.core.object_store import should_await

            if should_await(out):
                out = await out
            return out
        finally:
            if token is not None:
                from ray_tpu.util import tracing

                tracing.uninstall(token)


def _flush_nested_deferred(ids) -> None:
    """A result carrying refs to objects OWNED by this process's nested
    client runtime (direct-call returns it received and never shared) must
    upload them to the head before the result leaves — the consumer may be
    on any node and resolves contained refs through the directory."""
    if not ids:
        return
    from ray_tpu.core import runtime as core_runtime

    flush = getattr(core_runtime._runtime, "_flush_deferred_seals", None)
    if flush is not None:
        try:
            flush(ids)
        except Exception:  # noqa: BLE001 - best-effort
            logger.warning("nested deferred-seal flush failed", exc_info=True)


# the live Worker of this process (None in drivers/agents): node-local
# services that ride the worker's open arena handle — e.g. the serving
# plane's shared prefix cache — discover it here instead of re-mapping
# the arena per consumer
_CURRENT_WORKER: Optional["Worker"] = None


class Worker:
    def __init__(self, agent_address: str, worker_id: str, store_path: str):
        global _CURRENT_WORKER
        _CURRENT_WORKER = self
        self.worker_id = worker_id
        self.agent = RpcClient(agent_address)
        self.node_id = os.environ.get("RAY_TPU_NODE_ID", "")
        # distributed refcounting: this process reports releases through its
        # agent (which forwards to the head); the worker id is the holder id,
        # shared with any nested client runtime user code creates.
        from ray_tpu.core import refcount

        refcount.set_holder_id(worker_id)
        self._flusher = refcount.RefFlusher(
            lambda inc, dec: self.agent.call(
                "RefUpdate",
                {"holder": worker_id, "increfs": inc, "decrefs": dec},
                timeout=10.0,
            ),
            holder=worker_id,
        )
        refcount.install_consumer(self._flusher)
        # one deserialized fn per fn_id (see _fn_from_blob)
        self._fn_cache: Dict[str, Any] = {}
        self._fn_cache_order: deque = deque()
        # streaming-generator announcements, flushed with direct seals
        self._stream_reports: list = []
        self._stream_done_reports: list = []
        self.store = None
        if store_path:
            try:
                from ray_tpu.native import NativeObjectStore

                self.store = NativeObjectStore(path=store_path, create=False)
                # crash-durable view-pin sidecar: if this worker is
                # SIGKILLed with zero-copy views outstanding, the agent
                # replays the log and releases the pins (zombie-pin
                # reclamation) instead of leaking arena space until the
                # next arena restart
                self.store.enable_pin_tracking()
            except Exception:  # noqa: BLE001
                logger.warning("worker could not open shm store %s", store_path)
        self._actors: Dict[str, Any] = {}
        self._actor_loops: Dict[str, Any] = {}  # actor_id -> (loop, sems)
        self._trace_tokens = threading.local()  # per-thread trace token
        # runtime-env gate: tasks sharing ONE env signature run
        # concurrently (refcounted application); a DIFFERENT env waits for
        # the current one to drain. Env-less tasks skip the gate entirely
        # — they can observe a concurrently-applied env (process-level
        # isolation needs a dedicated worker, which actors get; the
        # reference isolates via per-env worker processes the same way).
        self._env_cv = threading.Condition()
        self._env_sig: Optional[str] = None
        self._env_active = 0
        self._env_undo = lambda: None
        from concurrent.futures import ThreadPoolExecutor

        # seals + TaskDone callbacks for finished async-actor methods run
        # here, off the event loop (put_value can RPC to the agent)
        self._done_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="task-done"
        )
        # completion coalescer: everything finished while the previous
        # TaskDoneBatch RPC was in flight merges into one message
        self._done_q: deque = deque()
        self._done_cv = threading.Condition()
        threading.Thread(
            target=self._done_sender_loop, name="task-done-send", daemon=True
        ).start()
        # batched pushes execute CONCURRENTLY: two granted leases must both
        # make progress even if they block on each other (e.g. collective
        # rendezvous between tasks) — sequential batch execution would
        # deadlock them.
        self._batch_pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="task-batch"
        )
        # asyncio loops being torn down by KillActor: batch task creation
        # must not slip new tasks past drain_and_stop's cancellation sweep
        self._stopping_loops: set = set()
        # compiled-DAG programs resident in this worker:
        # dag_id -> {"stop": Event, "threads": [...], "channels": [...]}
        self._dag_programs: Dict[str, dict] = {}
        # AOT-compiled pipeline stage programs (dag/pipeline.py):
        # pipe_id -> {"stop": Event, "threads": [(thread, channels)]}
        self._pipelines: Dict[str, dict] = {}
        # per-actor lock mediating DAG stage threads vs normal pushed
        # methods on the same instance (created when a DAG binds the actor)
        self._dag_actor_locks: Dict[str, threading.Lock] = {}
        # direct actor calls (actor_task_submitter analog): per-actor FIFO
        # executor threads for sync actors, result push-back to callers,
        # and seal reports to the agent for the head's object directory
        self._direct_fifo: Dict[str, deque] = {}
        self._direct_fifo_cv = threading.Condition()
        self._direct_fifo_threads: Dict[str, threading.Thread] = {}
        self._direct_out: Dict[str, list] = {}  # client_addr -> results
        self._direct_out_cv = threading.Condition()
        self._direct_clients: Dict[str, RpcClient] = {}
        self._direct_seals: list = []  # SealInfo batch for the agent
        self._direct_seal_cv = threading.Condition()
        # metrics federation (ISSUE 15): this worker's registry ships as
        # typed deltas on the seal channel (the agent relays them on its
        # next head report); created lazily on the first due tick so an
        # idle worker stays import-light
        self._metric_exporter = None
        self._metrics_last_ship = time.monotonic()
        threading.Thread(
            target=self._direct_sender_loop,
            name="direct-result-send",
            daemon=True,
        ).start()
        threading.Thread(
            target=self._direct_seal_loop,
            name="direct-seal-send",
            daemon=True,
        ).start()
        # leased-task execution (task leases: owner streams same-shape
        # tasks straight to this pinned worker): per-lease FIFO queue +
        # executor thread — ONE task runs at a time against the lease's
        # single resource allocation (multiplexing is pipelining depth,
        # not parallelism); results/seals ride the direct-call machinery
        self._lease_q: Dict[str, deque] = {}  # lease_id -> queued items
        self._lease_state: Dict[str, dict] = {}  # lease_id -> {released,undo}
        # released-lease tombstones: a stale owner batch arriving after
        # the FIFO drained must see "released" (and spill to the head),
        # never resurrect the lease on a worker already back in the pool
        self._lease_tombstones: set = set()
        self._lease_tombstone_order: deque = deque()
        self._lease_running: Dict[str, str] = {}  # lease_id -> ref executing
        self._lease_cv = threading.Condition()
        self._server = RpcServer(
            {
                "PushTask": self._h_push_task,
                "PushTaskBatch": self._h_push_task_batch,
                "KillActor": self._h_kill_actor,
                "ScrubActor": self._h_scrub_actor,
                "DagInstall": self._h_dag_install,
                "DagTeardown": self._h_dag_teardown,
                "PipelineInstall": self._h_pipeline_install,
                "PipelineTeardown": self._h_pipeline_teardown,
                "DirectPushBatch": self._h_direct_push_batch,
                "LeaseTaskBatch": self._h_lease_task_batch,
                "LeaseRecall": self._h_lease_recall,
                "LeaseRelease": self._h_lease_release,
                "LeaseKillRunning": self._h_lease_kill_running,
                "Ping": lambda r: "pong",
            },
            port=0,
            max_workers=8,
        )
        # pristine-state baseline for actor-worker reuse (ScrubActor):
        # everything user code adds past this point is what a scrub must
        # be able to undo — or the scrub is refused and the worker dies
        self._baseline_modules = frozenset(sys.modules)
        self._baseline_env = dict(os.environ)
        self._baseline_sys_path = list(sys.path)
        # strong refs to the Thread OBJECTS (idents recycle after a
        # thread exits; an object identity can't while we hold it)
        self._baseline_threads = frozenset(threading.enumerate())
        try:
            self._baseline_cwd = os.getcwd()
        except OSError:
            self._baseline_cwd = None
        self.agent.call(
            "RegisterWorker",
            {"worker_id": worker_id, "address": self._server.address},
            retries=20,
            retry_interval=0.1,
        )

    # ------------------------------------------------------------------
    # object plane helpers
    # ------------------------------------------------------------------
    def _loads_tracking(self, data: bytes) -> Any:
        from ray_tpu.core.refcount import loads_tracking

        return loads_tracking(self._flusher, data)

    def _read_local(self, hex_id: str) -> Any:
        """Same-node read: a zero-copy READ-ONLY view mapped over the
        shared arena page (numpy payloads reconstruct as views — no
        bytes ever cross a socket). cfg.worker_shm_reads=0 falls back to
        the copying read for debugging / A-B perf comparison."""
        from ray_tpu.config import cfg

        if cfg.worker_shm_reads:
            view = self.store.get_view(hex_id)
            OBJECT_TRANSFER_BYTES.inc(view.nbytes, labels={"path": "shm"})
            return self._loads_tracking(view)
        # distinct label so the A/B the flag exists for stays readable:
        # these bytes came from the arena but paid the copy
        data = self.store.get_bytes(hex_id)
        OBJECT_TRANSFER_BYTES.inc(len(data), labels={"path": "shm_copy"})
        return self._loads_tracking(data)

    def get_object(
        self,
        hex_id: str,
        timeout: Optional[float] = None,
        purpose: str = "task_args",
    ) -> Any:
        if self.store is not None:
            try:
                value = self._read_local(hex_id)
                SHM_HITS.inc()
                return value
            except (KeyError, BlockingIOError):
                SHM_MISSES.inc()
        reply = self.agent.call(
            "GetObjectForWorker",
            {"object_id": hex_id, "timeout": timeout, "purpose": purpose},
            timeout=None,
        )
        status = reply["status"]
        if status == "local":
            if self.store is not None:
                try:
                    # no SHM_HITS here: this logical read already counted
                    # as a miss above (the agent restored/located it) —
                    # counting a hit too would skew the hit rate
                    return self._read_local(hex_id)
                except (KeyError, BlockingIOError):
                    pass  # spilled/evicted between reply and read: fall back
            # our shm read failed but the agent can serve the bytes
            data = self.agent.call(
                "FetchObject", {"object_id": hex_id}, timeout=120.0
            )
            OBJECT_TRANSFER_BYTES.inc(len(data), labels={"path": "rpc"})
            return self._loads_tracking(data)
        if status == "inline":
            OBJECT_TRANSFER_BYTES.inc(
                len(reply["data"]), labels={"path": "inline"}
            )
            return self._loads_tracking(reply["data"])
        if status == "error":
            raise pickle.loads(reply["error"])
        raise TimeoutError(f"timed out fetching object {hex_id}")

    def put_value(self, object_id: str, value: Any) -> SealInfo:
        from ray_tpu.core.refcount import collect_serialized

        # pickle-5 out-of-band: numpy buffers stay separate frames — a
        # large block is ONE gather-copy into the shared arena, never a
        # monolithic pickle byte string re-copied per hop
        with collect_serialized() as contained:
            parts, total = wire.dumps_parts(value)
        contained_ids = sorted(contained)
        _flush_nested_deferred(contained_ids)
        if total <= INLINE_OBJECT_MAX:
            data = wire.join_parts(parts)
            OBJECT_TRANSFER_BYTES.inc(len(data), labels={"path": "inline"})
            return SealInfo(
                object_id=object_id,
                node_id=self.node_id,
                size=len(data),
                inline_value=data,
                contained_ids=contained_ids,
            )
        stored = False
        if self.store is not None:
            try:
                self.store.put_frames(object_id, parts)
                OBJECT_TRANSFER_BYTES.inc(total, labels={"path": "shm"})
                stored = True
            except Exception:  # noqa: BLE001 - arena full
                pass
        if not stored:
            self.agent.call(
                "WorkerPut",
                {"object_id": object_id, "data": wire.join_parts(parts)},
                timeout=60.0,
            )
            OBJECT_TRANSFER_BYTES.inc(total, labels={"path": "rpc"})
        return SealInfo(
            object_id=object_id,
            node_id=self.node_id,
            size=total,
            contained_ids=contained_ids,
        )

    # ------------------------------------------------------------------
    # runtime envs (the per-lease slice of _private/runtime_env/).
    # Isolation contract: a PLAIN task's env is applied for exactly its
    # execution and then undone (env_vars restored, injected sys.path
    # entries removed), and tasks carrying a runtime_env serialize on one
    # lock so two different envs can never interleave on a shared worker.
    # An ACTOR CREATION keeps its env for the worker's life (the actor
    # owns the process, same as its chip assignment). Modules already
    # imported from a working_dir stay imported — process-level isolation
    # needs a dedicated worker, which actors get by construction.
    # ------------------------------------------------------------------
    def _env_enter(self, env: dict) -> None:
        """Join the env gate: same-signature tasks share one application
        (refcounted — co-scheduled tasks of one job, e.g. collective
        rendezvous peers, run CONCURRENTLY); a different signature waits
        for the current one to drain, so two envs never interleave."""
        import json

        sig = json.dumps(env, sort_keys=True, default=str)
        with self._env_cv:
            while self._env_active > 0 and self._env_sig != sig:
                self._env_cv.wait(timeout=1.0)
            if self._env_active == 0:
                self._env_sig = sig
                self._env_undo = self._apply_runtime_env(env)
            self._env_active += 1

    def _env_exit(self, persist: bool = False) -> None:
        with self._env_cv:
            self._env_active -= 1
            if self._env_active == 0:
                if not persist:
                    self._env_undo()
                # an actor owns its worker: a persisted env's undo is
                # simply discarded
                self._env_undo = lambda: None
                self._env_sig = None
            self._env_cv.notify_all()

    def _apply_runtime_env(self, env: Optional[dict]):
        """Apply ``env``; returns an undo() closure (no-op when env is
        empty). Called under the env gate (_env_enter)."""
        if not env:
            return lambda: None
        prev_vars: Dict[str, Optional[str]] = {}
        for k, v in (env.get("env_vars") or {}).items():
            prev_vars[k] = os.environ.get(k)
            os.environ[k] = str(v)
        added_paths: List[str] = []
        for key in [env.get("working_dir"), *(env.get("py_modules") or [])]:
            if key and key not in sys.path:
                sys.path.insert(0, key)
                added_paths.append(key)
        if added_paths:
            importlib.invalidate_caches()

        def undo() -> None:
            for k, old in prev_vars.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            for p in added_paths:
                try:
                    sys.path.remove(p)
                except ValueError:
                    pass
            if added_paths:
                importlib.invalidate_caches()

        return undo

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def _run_streaming_task(self, req: dict, fn, args, kwargs) -> None:
        """Drive a ``num_returns="streaming"`` task (_raylet.pyx:246
        streaming-generator execution analog): each yield seals under
        stream_item_id(task_id, i) and is announced to the head through
        the async seal path; the executor pauses once it is
        cfg.streaming_window items ahead of the consumer's watermark
        (generator backpressure). ANY user-code exception — in the call
        itself or mid-iteration — seals an error item so the consumer's
        next ref raises, then ends the stream."""
        from ray_tpu.cluster.common import stream_item_id
        from ray_tpu.config import cfg

        window = max(1, int(cfg.streaming_window))
        tid = req["task_id"]
        idx = 0
        try:
            gen = fn(*args, **kwargs)
            if not hasattr(gen, "__next__"):
                gen = iter(gen)
        except BaseException as exc:  # noqa: BLE001 - errors are values
            self._end_stream(req, 0, exc)
            return
        consumed = 0
        while True:
            try:
                value = next(gen, _STREAM_END)
            except BaseException as exc:  # noqa: BLE001 - errors are values
                self._end_stream(req, idx, exc)
                return
            if value is _STREAM_END:
                self._end_stream(req, idx, None)
                return
            while idx - consumed >= window:
                try:
                    reply = self.agent.call(
                        "StreamConsumed",
                        {
                            "task_id": tid,
                            "after_consumed": consumed,
                            "timeout": 5.0,
                        },
                        timeout=20.0,
                    )
                except RpcError:
                    time.sleep(0.5)
                    continue
                consumed = reply["consumed"]
                if reply.get("abandoned"):
                    # consumer dropped the generator: stop producing
                    try:
                        gen.close()
                    except Exception:  # noqa: BLE001
                        pass
                    self._end_stream(req, idx, None)
                    return
            oid = stream_item_id(tid, idx)
            seal = self.put_value(oid, value)
            with self._direct_seal_cv:
                self._direct_seals.append(seal)
                self._stream_reports.append(
                    {"task_id": tid, "index": idx, "object_id": oid}
                )
                self._direct_seal_cv.notify()
            idx += 1

    def _end_stream(self, req: dict, count: int, exc) -> None:
        done: dict = {"task_id": req["task_id"], "count": count}
        if exc is not None:
            from ray_tpu.core.object_store import TaskError

            tb = traceback.format_exc()
            err = TaskError(exc, req["name"], traceback_str=tb)
            err.__cause__ = exc
            try:
                done["error"] = cloudpickle.dumps(err)
            except Exception:  # noqa: BLE001 - unpicklable exception
                done["error"] = cloudpickle.dumps(
                    TaskError(
                        RuntimeError(repr(exc)),
                        req["name"],
                        traceback_str=tb,
                    )
                )
        with self._direct_seal_cv:
            self._stream_done_reports.append(done)
            self._direct_seal_cv.notify()

    def _fn_from_blob(self, fn_id: str, blob: bytes, cacheable) -> Any:
        """Deserialize a task function once per (worker, fn_id).

        Repeated submissions of the same function ship the same blob
        (client pickles once, _serialize_fn); unpickling it per execution
        was the executor-side half of that cost. Not cached when the
        client marked it uncacheable (closure over ObjectRefs: per-call
        deserialization keeps ref lifetimes per-execution). Small LRU —
        eviction drops the fn and any refs it holds."""
        if not cacheable or not fn_id:
            return cloudpickle.loads(blob)
        cache = self._fn_cache
        fn = cache.get(fn_id)
        if fn is None:
            fn = cloudpickle.loads(blob)
            cache[fn_id] = fn
            self._fn_cache_order.append(fn_id)
            if len(self._fn_cache_order) > 64:
                cache.pop(self._fn_cache_order.popleft(), None)
        return fn

    def _resolve(self, args: tuple, kwargs: dict):
        from ray_tpu.core.object_store import ObjectRef

        def rv(x):
            return self.get_object(x.hex) if isinstance(x, ObjectRef) else x

        return tuple(rv(a) for a in args), {k: rv(v) for k, v in kwargs.items()}

    def _h_push_task(self, req: dict) -> dict:
        kind = req["kind"]
        self._set_context(req)
        accel_env = req.get("accel_env")
        prev_env: Dict[str, Optional[str]] = {}
        persist_env = False
        creation_ok = False
        runtime_env = req.get("runtime_env")
        if runtime_env:
            self._env_enter(runtime_env)
        try:
            if accel_env:
                # the granted lease's chip assignment: TPU_VISIBLE_CHIPS /
                # CUDA_VISIBLE_DEVICES (accelerators/tpu.py:38-56 analog).
                # A SUCCESSFUL actor creation keeps it for the pinned
                # worker's lifetime — the actor owns those chips. Every
                # other case (plain tasks, failed creations, methods with
                # their own demand) restores the prior values so a reused
                # worker — or the actor's own lifetime pin — is not
                # clobbered.
                prev_env = {k: os.environ.get(k) for k in accel_env}
                os.environ.update(accel_env)
            if kind == "actor_creation":
                cls, args, kwargs = wire.loads(req["payload"])
                args, kwargs = self._resolve(args, kwargs)
                from ray_tpu.core.actor import _coroutine_method_names

                aid = req["actor_id"]
                if _coroutine_method_names(cls):
                    # asyncio actor: one event loop owns all its methods
                    from ray_tpu.core.actor import (
                        DEFAULT_MAX_CONCURRENCY_ASYNC,
                    )

                    meta = req.get("actor_meta") or {}
                    mc = meta.get("max_concurrency")
                    # unset → asyncio default 1000; an explicit 1 serializes
                    mc = (
                        DEFAULT_MAX_CONCURRENCY_ASYNC
                        if mc is None
                        else max(1, int(mc))
                    )
                    groups = {"_default": mc}
                    groups.update(meta.get("concurrency_groups") or {})
                    self._actor_loops[aid] = self._start_actor_loop(aid, groups)
                self._actors[aid] = cls(*args, **kwargs)
                persist_env = bool(accel_env)  # actor now owns these chips
                creation_ok = True
                result_values: List[Any] = []
            elif kind == "actor_method":
                method, args, kwargs = wire.loads(req["payload"])
                args, kwargs = self._resolve(args, kwargs)
                aid = req["actor_id"]
                instance = self._actors[aid]
                entry = self._actor_loops.get(aid)
                if entry is not None and req.get("streaming"):
                    # async actors reply per-call through their event
                    # loop; the per-item stream plumbing is sync-only
                    self._end_stream(
                        req,
                        0,
                        TypeError(
                            "num_returns='streaming' is not supported on "
                            "async actors; use a sync actor or a task"
                        ),
                    )
                    result_values = []
                elif entry is not None:
                    # asyncio actor: schedule on the actor's loop and reply
                    # "async_pending" NOW — the outcome goes back to the
                    # agent via TaskDone when the coroutine finishes. No
                    # thread is held per in-flight method, so thousands can
                    # park on awaits (reference asyncio-actor semantics).
                    import asyncio

                    loop, sems = entry
                    fut = asyncio.run_coroutine_threadsafe(
                        _invoke_maybe_async(
                            instance, method, args, kwargs, sems,
                            trace=req.get("trace"),
                        ),
                        loop,
                    )
                    fut.add_done_callback(
                        lambda f, r=req: self._done_pool.submit(
                            self._finish_async_task, r, f
                        )
                    )
                    return {"status": "async_pending"}
                if req.get("streaming"):
                    # sync actors only (an async actor's loop replies
                    # async_pending above and never reaches here with
                    # streaming — guarded by the lease route)
                    self._run_streaming_task(
                        req, getattr(instance, method), args, kwargs
                    )
                    result_values = []
                else:
                    dag_lock = self._dag_actor_locks.get(aid)
                    if dag_lock is not None:
                        with dag_lock:
                            out = getattr(instance, method)(*args, **kwargs)
                    else:
                        out = getattr(instance, method)(*args, **kwargs)
                    result_values = self._split(out, req["return_ids"])
            else:
                fn_blob = req.get("fn_blob")
                if fn_blob is not None:
                    fn = self._fn_from_blob(
                        req.get("fn_id", ""), fn_blob, req.get("fn_cache")
                    )
                    args, kwargs = wire.loads(req["payload"])
                else:
                    fn, args, kwargs = wire.loads(req["payload"])
                args, kwargs = self._resolve(args, kwargs)
                if req.get("streaming"):
                    # owns ALL user-code exceptions (sealed as the final
                    # stream item) — a raise here would end the lease
                    # without a stream-done marker and hang the consumer
                    self._run_streaming_task(req, fn, args, kwargs)
                    result_values = []
                else:
                    out = fn(*args, **kwargs)
                    result_values = self._split(out, req["return_ids"])
        except BaseException as exc:  # noqa: BLE001 - errors are values
            return self._error_reply(req, exc)
        finally:
            if accel_env and not persist_env:
                for k, old in prev_env.items():
                    if old is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = old
            if runtime_env:
                self._env_exit(persist=creation_ok)
            self._clear_context()
        try:
            # sealing can fail too (store full + agent fallback unreachable):
            # that MUST become an error reply, not an exception escaping the
            # RPC handler — the agent would leak the lease's resources
            seals = [
                self.put_value(oid, v)
                for oid, v in zip(req["return_ids"], result_values)
            ]
            reply = {"status": "ok", "seals": seals}
            borrows = self._compute_borrows(req.get("arg_ids"))
            if borrows:
                reply["borrows"] = borrows
        except BaseException as exc:  # noqa: BLE001
            return self._error_reply(req, exc)
        if kind == "actor_creation" and req["actor_id"] in self._actor_loops:
            # tells the agent to skip per-actor FIFO serialization
            reply["async_actor"] = True
        return reply

    def _h_push_task_batch(self, reqs: List[dict]) -> List[dict]:
        if len(reqs) == 1:
            return [self._h_push_task(reqs[0])]
        futs = [self._batch_pool.submit(self._h_push_task, r) for r in reqs]
        return [f.result() for f in futs]

    def _compute_borrows(self, arg_ids) -> List[str]:
        """Arg refs this process still holds at task completion (stored in
        actor state or a live closure): reported in the completion reply so
        the head converts the lease's arg pin into a holder count before
        releasing it (borrower registration, reference_counter.h borrows)."""
        from ray_tpu.core.refcount import TRACKER

        borrowed = [
            h
            for h in arg_ids or ()
            if TRACKER.count(h) > 0 and not self._flusher.is_registered(h)
        ]
        if borrowed:
            self._flusher.note_registered(borrowed)
        return borrowed

    def _start_actor_loop(self, actor_id: str, groups: Dict[str, int]):
        """Returns (loop, {group: semaphore}); semaphores bind to the loop."""
        import asyncio

        loop = asyncio.new_event_loop()
        ready = threading.Event()
        sems: Dict[str, Any] = {}

        def run() -> None:
            asyncio.set_event_loop(loop)
            for g, limit in groups.items():
                sems[g] = asyncio.Semaphore(max(1, int(limit)))
            ready.set()
            loop.run_forever()

        threading.Thread(
            target=run, name=f"actor-loop-{actor_id[:6]}", daemon=True
        ).start()
        ready.wait()
        return loop, sems

    def _error_reply(self, req: dict, exc: BaseException) -> dict:
        """Build the failure reply: errors are values (sealed TaskError)."""
        if req.get("retry_exceptions"):
            return {"status": "retry", "error_repr": repr(exc)}
        tb = traceback.format_exc()
        logger.debug("task %s failed:\n%s", req["name"], tb)
        from ray_tpu.core.object_store import TaskError

        err = TaskError(exc, req["name"], traceback_str=tb)
        err.__cause__ = exc
        try:
            blob = cloudpickle.dumps(err)
        except Exception:  # noqa: BLE001 - unpicklable exception
            blob = cloudpickle.dumps(
                TaskError(RuntimeError(repr(exc)), req["name"], traceback_str=tb)
            )
        seals = [
            SealInfo(
                object_id=oid,
                node_id=self.node_id,
                is_error=True,
                error=blob,
            )
            for oid in req["return_ids"]
        ]
        return {"status": "error", "error_repr": repr(exc), "seals": seals}

    def _finish_async_task(self, req: dict, fut) -> None:
        """Runs in the done-pool when an async method's coroutine settles:
        seal results, then hand the outcome to the agent (TaskDone)."""
        try:
            try:
                out = fut.result()
                result_values = self._split(out, req["return_ids"])
                seals = [
                    self.put_value(oid, v)
                    for oid, v in zip(req["return_ids"], result_values)
                ]
                reply = {"status": "ok", "seals": seals}
                borrows = self._compute_borrows(req.get("arg_ids"))
                if borrows:
                    reply["borrows"] = borrows
            except BaseException as exc:  # noqa: BLE001 - errors are values
                reply = self._error_reply(req, exc)
            with self._done_cv:
                self._done_q.append(
                    {"task_id": req["task_id"], "reply": reply}
                )
                self._done_cv.notify()
        except Exception:  # noqa: BLE001
            logger.exception("async task completion failed")

    def _done_sender_loop(self) -> None:
        while True:
            with self._done_cv:
                while not self._done_q:
                    self._done_cv.wait(timeout=1.0)
                batch = list(self._done_q)
                self._done_q.clear()
            try:
                self.agent.call("TaskDoneBatch", batch, timeout=60.0)
            except RpcError:
                logger.warning(
                    "agent unreachable; dropping %d TaskDones", len(batch)
                )

    def _split(self, out: Any, return_ids: List[str]) -> List[Any]:
        if len(return_ids) <= 1:
            return [out] if return_ids else []
        values = tuple(out)
        if len(values) != len(return_ids):
            raise ValueError(
                f"task returned {len(values)} values, expected {len(return_ids)}"
            )
        return list(values)

    def _set_context(self, req: dict) -> None:
        try:
            from ray_tpu.core.runtime import get_context
            from ray_tpu.util import tracing

            ctx = get_context()
            ctx.node_id = self.node_id
            ctx.task_id = req["task_id"]
            ctx.actor_id = req.get("actor_id")
            # install the received trace context so nested submissions
            # from this task inherit the SAME trace id with this task as
            # their parent span (tracing_helper.py propagation). The token
            # is thread-local: batched pushes run _h_push_task on
            # concurrent pool threads, each with its own context.
            self._trace_tokens.token = tracing.install(req.get("trace"))
        except Exception:  # noqa: BLE001
            pass

    def _clear_context(self) -> None:
        try:
            from ray_tpu.core.runtime import get_context
            from ray_tpu.util import tracing

            ctx = get_context()
            ctx.node_id = None
            ctx.task_id = None
            ctx.actor_id = None
            token = getattr(self._trace_tokens, "token", None)
            if token is not None:
                self._trace_tokens.token = None
                tracing.uninstall(token)
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------------
    # direct actor calls (reference: actor_task_submitter.cc caller->worker
    # submission + task_receiver.h execution, bypassing GCS/raylet).
    # The accept reply returns as soon as every item is QUEUED; results are
    # pushed back to the caller's callback server (coalesced), and seals
    # flow to the agent so the head's object directory stays authoritative
    # for non-owner consumers.
    # ------------------------------------------------------------------

    def _h_direct_push_batch(self, req: dict) -> List[Any]:
        """Accept a batch of direct method calls. Per item the reply entry
        is "accepted" / "unknown_actor" / {"done": result}: after queueing
        everything, the handler lingers a few ms so fast results ride the
        accept reply itself — one RPC round trip for the common case —
        while slow methods fall back to the pushed DirectResults path
        (bounded wait, so a parked method can never deadlock the wire)."""
        import concurrent.futures as cf

        client_addr = req["client_addr"]
        accepts: List[Any] = []
        waiters: List[Optional[cf.Future]] = []
        from ray_tpu.config import cfg

        if cfg.direct_trace:
            for item in req["items"]:
                item["_t_accept"] = time.perf_counter()
        # batch event-loop handoff: scheduling N coroutines with ONE
        # call_soon_threadsafe instead of N run_coroutine_threadsafe calls
        # saves N-1 cross-thread wakeups per accepted batch
        loop_batches: Dict[int, list] = {}
        for item in req["items"]:
            aid = item["actor_id"]
            instance = self._actors.get(aid)
            if instance is None:
                accepts.append("unknown_actor")
                waiters.append(None)
                continue
            item["client_addr"] = client_addr
            item["_claim"] = threading.Lock()
            item["_claimed"] = False
            entry = self._actor_loops.get(aid)
            if entry is not None:
                prepared = self._direct_prepare_async(item, instance, entry)
                if prepared is None:
                    fut = None  # ref args: deferred resolve path
                else:
                    coro, fut = prepared
                    loop_batches.setdefault(id(entry[0]), [entry[0], []])[
                        1
                    ].append((coro, fut))
            else:
                fut = self._direct_fifo_enqueue(aid, item)
            accepts.append("accepted")
            waiters.append(fut)
        for loop, pairs in loop_batches.values():
            self._schedule_coro_batch(loop, pairs)
        live = [f for f in waiters if f is not None]
        if live:
            from ray_tpu.config import cfg

            cf.wait(live, timeout=cfg.direct_inline_wait_s)
        for i, (item, fut) in enumerate(zip(req["items"], waiters)):
            if fut is None:
                continue  # deferred dispatch attaches its own callback
            if fut.done():
                with item["_claim"]:
                    if item["_claimed"]:
                        continue
                    item["_claimed"] = True
                try:
                    result, seal = self._build_direct_result(
                        item, fut.result()
                    )
                except BaseException as exc:  # noqa: BLE001
                    result, seal = self._build_direct_error(item, exc)
                if seal is not None:  # deferred: caller owns bookkeeping
                    with self._direct_seal_cv:
                        self._direct_seals.append(seal)
                        self._direct_seal_cv.notify()
                accepts[i] = {"done": result}
            else:
                # still running: results go via the pushed DirectResults
                # path once the method settles
                fut.add_done_callback(
                    lambda f, it=item: self._done_pool.submit(
                        self._direct_finish_future, it, f
                    )
                )
        return accepts

    def _direct_prepare_async(self, item: dict, instance, entry):
        """Returns (coroutine, future) for batch scheduling, or None when
        arg refs defer resolution to the done pool (which schedules and
        attaches its own completion callback)."""
        import asyncio

        from ray_tpu.core.object_store import ObjectRef

        loop, sems = entry
        method, args, kwargs = wire.loads(item["payload"])

        has_refs = any(isinstance(a, ObjectRef) for a in args) or any(
            isinstance(v, ObjectRef) for v in kwargs.values()
        )
        if not has_refs:
            import concurrent.futures as cf

            coro = _invoke_maybe_async(
                instance, method, args, kwargs, sems,
                trace=item.get("trace"),
            )
            return coro, cf.Future()

        # arg fetches can block: resolve off the event loop AND off the
        # RPC handler thread (the accept reply must return promptly)
        def resolve_then_schedule() -> None:
            try:
                rargs, rkwargs = self._resolve(args, kwargs)
            except BaseException as exc:  # noqa: BLE001
                self._direct_finish_claimed_error(item, exc)
                return
            fut = asyncio.run_coroutine_threadsafe(
                _invoke_maybe_async(
                    instance, method, rargs, rkwargs, sems,
                    trace=item.get("trace"),
                ),
                loop,
            )
            fut.add_done_callback(
                lambda f, it=item: self._done_pool.submit(
                    self._direct_finish_future, it, f
                )
            )

        self._done_pool.submit(resolve_then_schedule)
        return None

    def _schedule_coro_batch(self, loop, pairs) -> None:
        """Create all of a batch's tasks on the loop in one hop, bridging
        each asyncio task to its concurrent Future."""

        def create_all() -> None:
            if id(loop) in self._stopping_loops:
                # KillActor is draining this loop: creating tasks now
                # would slip them past the cancellation sweep and leave
                # their futures unresolved forever
                import concurrent.futures as cf

                for coro, cfut in pairs:
                    coro.close()
                    if cfut.set_running_or_notify_cancel():
                        cfut.set_exception(
                            RuntimeError("actor is being killed")
                        )
                return
            for coro, cfut in pairs:
                task = loop.create_task(coro)

                def done(t, cfut=cfut):
                    if not cfut.set_running_or_notify_cancel():
                        return
                    exc = None if t.cancelled() else t.exception()
                    if t.cancelled():
                        import concurrent.futures as cf

                        cfut.set_exception(cf.CancelledError())
                    elif exc is not None:
                        cfut.set_exception(exc)
                    else:
                        cfut.set_result(t.result())

                task.add_done_callback(done)

        loop.call_soon_threadsafe(create_all)

    def _direct_finish_future(self, item: dict, fut) -> None:
        """Callback-path completion: only fires the result push if the
        accept handler didn't already claim this item inline."""
        with item["_claim"]:
            if item["_claimed"]:
                return
            item["_claimed"] = True
        try:
            try:
                result, seal = self._build_direct_result(item, fut.result())
            except BaseException as exc:  # noqa: BLE001
                result, seal = self._build_direct_error(item, exc)
            self._direct_emit(item["client_addr"], result, seal)
        except Exception:  # noqa: BLE001
            logger.exception("direct call completion failed")

    def _direct_finish_claimed_error(self, item: dict, exc: BaseException) -> None:
        with item["_claim"]:
            if item["_claimed"]:
                return
            item["_claimed"] = True
        result, seal = self._build_direct_error(item, exc)
        self._direct_emit(item["client_addr"], result, seal)

    def _direct_fifo_enqueue(self, actor_id: str, item: dict):
        """Sync actor: one FIFO thread per actor preserves per-caller method
        order (the sender ships batches in submission order). Returns a
        Future of the raw value, completed by the FIFO thread."""
        import concurrent.futures as cf

        fut: cf.Future = cf.Future()
        with self._direct_fifo_cv:
            self._direct_fifo.setdefault(actor_id, deque()).append(
                (item, fut)
            )
            if actor_id not in self._direct_fifo_threads:
                t = threading.Thread(
                    target=self._direct_fifo_loop,
                    args=(actor_id,),
                    name=f"direct-{actor_id[:6]}",
                    daemon=True,
                )
                self._direct_fifo_threads[actor_id] = t
                t.start()
            self._direct_fifo_cv.notify_all()
        return fut

    def _direct_fifo_loop(self, actor_id: str) -> None:
        q = self._direct_fifo[actor_id]
        lock = self._dag_actor_locks.setdefault(actor_id, threading.Lock())
        while True:
            with self._direct_fifo_cv:
                while not q:
                    self._direct_fifo_cv.wait(timeout=5.0)
                    if not q and actor_id not in self._actors:
                        self._direct_fifo_threads.pop(actor_id, None)
                        return
                item, fut = q.popleft()
            try:
                instance = self._actors[actor_id]
                method, args, kwargs = wire.loads(item["payload"])
                args, kwargs = self._resolve(args, kwargs)
                from ray_tpu.util import tracing

                token = tracing.install(item.get("trace"))
                try:
                    with lock:
                        out = getattr(instance, method)(*args, **kwargs)
                finally:
                    tracing.uninstall(token)
                fut.set_result(out)
            except BaseException as exc:  # noqa: BLE001
                fut.set_exception(exc)

    def _register_direct_borrows(self, item: dict) -> None:
        """Arg refs this process still holds at completion (stored in actor
        state / a live closure) are registered with the head SYNCHRONOUSLY
        before the result is emitted — the caller releases its per-call arg
        pins once the result arrives, so the registration must already be
        on the books (lease-path analog: _compute_borrows + head pin
        conversion)."""
        from ray_tpu.core.refcount import TRACKER

        borrowed = [
            h
            for h in item.get("arg_ids") or ()
            if TRACKER.count(h) > 0 and not self._flusher.is_registered(h)
        ]
        if borrowed:
            self._flusher.sync_incref(borrowed)

    def _build_direct_result(self, item: dict, value: Any):
        """(result_dict, seal): inline small values ride back to the caller
        with an inline seal for the head's directory; large values go to
        the store with a location seal."""
        from ray_tpu.core.refcount import collect_serialized

        self._register_direct_borrows(item)
        oid = item["ref"]
        owner = item["client_id"]
        with collect_serialized() as contained:
            parts, total = wire.dumps_parts(value)
        contained_ids = sorted(contained)
        _flush_nested_deferred(contained_ids)
        data = wire.join_parts(parts) if total <= INLINE_OBJECT_MAX else b""
        if total <= INLINE_OBJECT_MAX:
            seal = SealInfo(
                object_id=oid,
                node_id=self.node_id,
                size=len(data),
                inline_value=data,
                contained_ids=contained_ids,
                owner=owner,
            )
            result = {"ref": oid, "status": "ok", "value": data}
            from ray_tpu.config import cfg as _cfg

            if _cfg.direct_deferred_seals and not contained_ids:
                # ownership model: the caller (owner) keeps value + seal;
                # the head learns about this object only if the ref is
                # shared or evicted (reference: small direct-call returns
                # never touch the GCS). The sender loop re-materializes
                # this seal worker-side if the result push fails.
                # Results CONTAINING refs keep the seal path — the seal is
                # what pins the inner objects head-side, and no caller-side
                # registration could close that race window.
                result["deferred_seal"] = contained_ids
                result["owner"] = owner
                seal = None
            if "_t_accept" in item:
                result["_t_accept"] = item["_t_accept"]
                result["_t_emit"] = time.perf_counter()
            return result, seal
        stored = False
        if self.store is not None:
            try:
                self.store.put_frames(oid, parts)
                OBJECT_TRANSFER_BYTES.inc(total, labels={"path": "shm"})
                stored = True
            except Exception:  # noqa: BLE001 - arena full
                pass
        if not stored:
            self.agent.call(
                "WorkerPut",
                {"object_id": oid, "data": wire.join_parts(parts)},
                timeout=60.0,
            )
            OBJECT_TRANSFER_BYTES.inc(total, labels={"path": "rpc"})
        seal = SealInfo(
            object_id=oid,
            node_id=self.node_id,
            size=total,
            contained_ids=contained_ids,
            owner=owner,
        )
        return {"ref": oid, "status": "seal", "seal": seal}, seal

    def _build_direct_error(self, item: dict, exc: BaseException):
        from ray_tpu.core.object_store import TaskError

        try:
            self._register_direct_borrows(item)
        except Exception:  # noqa: BLE001 - borrow RPC failure
            logger.warning("borrow registration failed", exc_info=True)
        tb = traceback.format_exc()
        err = TaskError(exc, item.get("name", "direct_call"), traceback_str=tb)
        err.__cause__ = exc
        try:
            blob = cloudpickle.dumps(err)
        except Exception:  # noqa: BLE001
            blob = cloudpickle.dumps(
                TaskError(
                    RuntimeError(repr(exc)),
                    item.get("name", "direct_call"),
                    traceback_str=tb,
                )
            )
        seal = SealInfo(
            object_id=item["ref"],
            node_id=self.node_id,
            is_error=True,
            error=blob,
            owner=item["client_id"],
        )
        return {"ref": item["ref"], "status": "error", "error": blob}, seal

    def _direct_emit(self, client_addr: str, result: dict, seal) -> None:
        with self._direct_out_cv:
            self._direct_out.setdefault(client_addr, []).append(result)
            self._direct_out_cv.notify()
        if seal is None:  # deferred: caller owns the bookkeeping
            return
        with self._direct_seal_cv:
            self._direct_seals.append(seal)
            self._direct_seal_cv.notify()

    def _direct_sender_loop(self) -> None:
        """Coalescing pusher: everything finished while the previous RPC
        was in flight merges into one DirectResults per caller. Seal
        reports ride a separate thread so the latency-critical result
        push never waits behind an agent round trip."""
        while True:
            with self._direct_out_cv:
                while not self._direct_out:
                    self._direct_out_cv.wait(timeout=1.0)
                out = self._direct_out
                self._direct_out = {}
            for addr, results in out.items():
                client = self._direct_clients.get(addr)
                if client is None:
                    client = self._direct_clients[addr] = RpcClient(addr)
                try:
                    client.call("DirectResults", results, timeout=30.0)
                except RpcError:
                    # caller is gone. Results with deferred seals were
                    # counting on the caller for head bookkeeping — seal
                    # them worker-side now so any other holder can still
                    # resolve through the directory.
                    fallback = [
                        SealInfo(
                            object_id=r["ref"],
                            node_id=self.node_id,
                            size=len(r["value"]),
                            inline_value=r["value"],
                            contained_ids=list(r["deferred_seal"] or ()),
                            owner=r.get("owner"),
                        )
                        for r in results
                        if r.get("status") == "ok"
                        and "deferred_seal" in r
                    ]
                    if fallback:
                        with self._direct_seal_cv:
                            self._direct_seals.extend(fallback)
                            self._direct_seal_cv.notify()
                    logger.warning(
                        "direct caller %s unreachable; dropping %d results",
                        addr,
                        len(results),
                    )

    def _metrics_due(self) -> bool:
        from ray_tpu.config import cfg

        return bool(cfg.metrics_federation) and (
            time.monotonic() - self._metrics_last_ship
            >= cfg.metrics_interval_s
        )

    def _metrics_entries(self) -> list:
        """Metrics federation tick (interval-gated): sync the dark-plane
        accumulators into this process's registry and collect its typed
        deltas, pre-labeled with this worker's node/role so they ride
        the agent's next head report untouched."""
        from ray_tpu.config import cfg

        if not cfg.metrics_federation:
            return []
        now = time.monotonic()
        if now - self._metrics_last_ship < cfg.metrics_interval_s:
            return []
        self._metrics_last_ship = now
        try:
            from ray_tpu.cluster.event_loop import publish_dark_plane
            from ray_tpu.util.metrics import DeltaExporter

            publish_dark_plane()
            if self._metric_exporter is None:
                self._metric_exporter = DeltaExporter()
            records = self._metric_exporter.collect()
        except Exception:  # noqa: BLE001 - metrics must not stall seals
            logger.debug("worker metrics collect failed", exc_info=True)
            return []
        if not records:
            return []
        # role carries a stable per-process discriminator: two workers
        # on one node must not collapse to the same series key (their
        # per-process gauges would overwrite each other; counters still
        # sum correctly across the per-worker series)
        return [
            {
                "node": self.node_id,
                "role": f"worker:{self.worker_id[:8]}",
                "records": records,
            }
        ]

    def _direct_seal_loop(self) -> None:
        while True:
            with self._direct_seal_cv:
                while not (
                    self._direct_seals
                    or self._stream_reports
                    or self._stream_done_reports
                ):
                    self._direct_seal_cv.wait(timeout=1.0)
                    # the seal channel doubles as the metrics uplink: a
                    # due tick breaks the wait even with nothing sealed
                    if self._metrics_due():
                        break
                seals = self._direct_seals
                self._direct_seals = []
                stream = self._stream_reports
                self._stream_reports = []
                stream_done = self._stream_done_reports
                self._stream_done_reports = []
            msg = {"seals": seals}
            if stream:
                msg["stream"] = stream
            if stream_done:
                msg["stream_done"] = stream_done
            metrics = self._metrics_entries()
            if metrics:
                msg["metrics"] = metrics
            if not (seals or stream or stream_done or metrics):
                continue
            while True:
                try:
                    self.agent.call("WorkerSealed", msg, timeout=30.0)
                    break
                except RpcError:
                    # a dropped seal would orphan the object in the head's
                    # directory (no location, no holder) — keep the batch
                    # and retry; if the agent is gone for good the orphan
                    # check in serve_forever exits this process
                    logger.warning(
                        "agent unreachable; retrying %d direct seals",
                        len(seals),
                    )
                    time.sleep(0.5)

    # ------------------------------------------------------------------
    # leased-task execution (task leases; reference: the raylet's worker
    # lease — one worker pinned to a submitter, tasks streamed to it with
    # no per-task scheduler hop, local_lease_manager.h). Tasks execute
    # STRICTLY one at a time per lease (the lease holds exactly one
    # task's resource allocation); queued items are recallable so the
    # owner can spill them back to head scheduling when the head of the
    # line blocks (rendezvous peers) or on explicit cancel. Results and
    # seals ride the direct-call result/seal machinery, so the head's
    # object directory stays authoritative exactly as for direct actor
    # calls (owner-held deferred seals included).
    # ------------------------------------------------------------------

    def _h_lease_task_batch(self, req: dict) -> List[str]:
        """Accept a window of leased tasks onto the lease's FIFO. The
        reply returns as soon as everything is queued; results push back
        to the caller's callback server. "released" tells a stale caller
        its lease is gone (it re-routes through the head)."""
        lease_id = req["lease_id"]
        client_addr = req["client_addr"]
        accel_env = req.get("accel_env")
        with self._lease_cv:
            if lease_id in self._lease_tombstones:
                return ["released"] * len(req["items"])
            st = self._lease_state.get(lease_id)
            if st is None:
                st = self._lease_state[lease_id] = {
                    "released": False,
                    "undo": None,
                }
                if accel_env:
                    # the lease owns this worker until released: its chip
                    # assignment applies for the lease lifetime (the
                    # actor-creation persistence semantics, scoped to the
                    # lease instead of the process)
                    prev = {k: os.environ.get(k) for k in accel_env}
                    os.environ.update(accel_env)

                    def undo(prev=prev) -> None:
                        for k, old in prev.items():
                            if old is None:
                                os.environ.pop(k, None)
                            else:
                                os.environ[k] = old

                    st["undo"] = undo
                self._lease_q[lease_id] = deque()
                threading.Thread(
                    target=self._lease_fifo_loop,
                    args=(lease_id,),
                    # "direct-" prefix: framework thread, scrub-allowed
                    name=f"direct-lease-{lease_id[:6]}",
                    daemon=True,
                ).start()
            elif st["released"]:
                return ["released"] * len(req["items"])
            q = self._lease_q[lease_id]
            for item in req["items"]:
                item["client_addr"] = client_addr
                q.append(item)
            self._lease_cv.notify_all()
        return ["accepted"] * len(req["items"])

    def _h_lease_recall(self, req: dict) -> dict:
        """Hand queued (not-yet-running) items back to the caller: with
        ``refs`` a targeted cancel, without it a stall spill — the owner
        re-routes the removed tasks through head scheduling. The running
        head-of-line task is never touched (non-force semantics)."""
        lease_id = req["lease_id"]
        only = req.get("refs")
        removed: List[str] = []
        with self._lease_cv:
            q = self._lease_q.get(lease_id)
            if q:
                keep: deque = deque()
                for item in q:
                    if only is None or item["ref"] in only:
                        removed.append(item["ref"])
                    else:
                        keep.append(item)
                self._lease_q[lease_id] = keep
                self._lease_cv.notify_all()
        return {"removed": removed}

    def _h_lease_release(self, req: dict) -> dict:
        """The agent reclaimed this lease's worker. Queued (not-yet-
        started) items are handed BACK to their owner as ``spill``
        results — it re-routes them through head scheduling — so the
        pooled worker only overlaps its next task with at most the one
        leased task already running; the FIFO thread exits (and undoes
        the lease env) once that finishes. A tombstone keeps stale
        owner batches from resurrecting the lease."""
        lease_id = req["lease_id"]
        drained: List[dict] = []
        with self._lease_cv:
            self._lease_tombstones.add(lease_id)
            self._lease_tombstone_order.append(lease_id)
            while len(self._lease_tombstone_order) > 1024:
                self._lease_tombstones.discard(
                    self._lease_tombstone_order.popleft()
                )
            st = self._lease_state.get(lease_id)
            if st is not None:
                st["released"] = True
                q = self._lease_q.get(lease_id)
                if q:
                    drained.extend(q)
                    q.clear()
                self._lease_cv.notify_all()
        for item in drained:
            self._direct_emit(
                item["client_addr"],
                {"ref": item["ref"], "status": "spill"},
                None,
            )
        return {"ok": True}

    def _lease_fifo_loop(self, lease_id: str) -> None:
        while True:
            item = None
            undo = None
            with self._lease_cv:
                while True:
                    st = self._lease_state.get(lease_id)
                    if st is None:
                        return
                    q = self._lease_q.get(lease_id)
                    if q:
                        item = q.popleft()
                        break
                    if st["released"]:
                        undo = st.get("undo")
                        self._lease_q.pop(lease_id, None)
                        self._lease_state.pop(lease_id, None)
                        break
                    self._lease_cv.wait(timeout=1.0)
            if item is None:
                if undo is not None:
                    undo()
                return
            self._lease_running[lease_id] = item["ref"]
            try:
                self._run_lease_item(item)
            finally:
                self._lease_running.pop(lease_id, None)

    def _h_lease_kill_running(self, req: dict) -> dict:
        """Force-cancel of the CURRENTLY EXECUTING leased task: the only
        preemption a thread-based executor has is killing the process —
        exactly what the head's force path does to a worker running a
        head-scheduled task. The agent's death path respawns the worker
        and reports the lease lost; the caller pre-seals the cancel."""
        if self._lease_running.get(req["lease_id"]) != req["ref"]:
            return {"ok": False}  # finished (or never started) meanwhile
        import threading as _threading

        _threading.Timer(0.1, lambda: os._exit(1)).start()
        return {"ok": True}

    def _run_lease_item(self, item: dict) -> None:
        """Execute one leased task and emit its result through the
        direct-call result path (seal bookkeeping identical to direct
        actor calls: inline values owner-held under deferred seals, big
        values sealed to the node store, errors sealed with owner)."""
        self._set_context(item)
        runtime_env = item.get("runtime_env")
        if runtime_env:
            self._env_enter(runtime_env)
        out = None
        failed: Optional[BaseException] = None
        sample = dispatch_sampled()
        t0 = time.perf_counter() if sample else 0.0
        try:
            fn = self._fn_from_blob(
                item.get("fn_id", ""), item["fn_blob"], item.get("fn_cache")
            )
            args, kwargs = wire.loads(item["payload"])
            args, kwargs = self._resolve(args, kwargs)
            out = fn(*args, **kwargs)
        except BaseException as exc:  # noqa: BLE001 - errors are values
            failed = exc
        finally:
            if sample:
                DISPATCH_OVERHEAD_US.observe(
                    (time.perf_counter() - t0) * 1e6, {"stage": "execute"}
                )
            if runtime_env:
                self._env_exit()
            self._clear_context()
        try:
            if failed is not None:
                result, seal = self._build_direct_error(item, failed)
            else:
                result, seal = self._build_direct_result(item, out)
        except BaseException as exc:  # noqa: BLE001 - sealing can fail too
            result, seal = self._build_direct_error(item, exc)
        self._direct_emit(item["client_addr"], result, seal)

    # ------------------------------------------------------------------
    # compiled-DAG programs (reference: compiled_dag_node.py actor-side
    # execution loops reading/writing channels instead of receiving tasks)
    # ------------------------------------------------------------------
    def _h_dag_install(self, req: dict) -> dict:
        from ray_tpu.dag.channel import ShmChannel
        from ray_tpu.dag.compiled import run_dag_stage

        actor_id = req["actor_id"]
        dag_id = req["dag_id"]
        instance = self._actors[actor_id]
        entry = self._actor_loops.get(actor_id)
        dag_lock = self._dag_actor_locks.setdefault(actor_id, threading.Lock())
        state = self._dag_programs.setdefault(
            dag_id, {"stop": threading.Event(), "threads": []}
        )
        for prog in req["programs"]:
            prog_channels: List[Any] = []
            in_channels: Dict[tuple, Any] = {}
            consts_args: List[Any] = []
            for i, (kind, v) in enumerate(prog["args"]):
                if kind == "chan":
                    ch = ShmChannel(v, capacity=prog["capacity"])
                    in_channels[("arg", i)] = ch
                    prog_channels.append(ch)
                    consts_args.append(None)
                else:
                    consts_args.append(cloudpickle.loads(v))
            consts_kwargs: Dict[str, Any] = {}
            for k, (kind, v) in prog["kwargs"].items():
                if kind == "chan":
                    ch = ShmChannel(v, capacity=prog["capacity"])
                    in_channels[("kw", k)] = ch
                    prog_channels.append(ch)
                    consts_kwargs[k] = None
                else:
                    consts_kwargs[k] = cloudpickle.loads(v)
            if prog.get("tick_path"):
                ch = ShmChannel(prog["tick_path"], capacity=prog["capacity"])
                in_channels[("tick",)] = ch
                prog_channels.append(ch)
            out_channels = []
            for p in prog["out_paths"]:
                ch = ShmChannel(p, capacity=prog["capacity"])
                out_channels.append(ch)
                prog_channels.append(ch)
            method = prog["method"]
            fn = getattr(instance, method)
            if entry is not None:
                import asyncio
                import inspect

                loop, _sems = entry

                def target(*a, _fn=fn, **kw):
                    from ray_tpu.core.object_store import should_await

                    with dag_lock:
                        out = _fn(*a, **kw)
                    if should_await(out):
                        return asyncio.run_coroutine_threadsafe(
                            _awrap(out), loop
                        ).result()
                    return out

                async def _awrap(aw):
                    # run_coroutine_threadsafe needs a coroutine, not a
                    # bare awaitable
                    return await aw

            else:

                def target(*a, _fn=fn, **kw):
                    with dag_lock:
                        return _fn(*a, **kw)
            t = threading.Thread(
                target=run_dag_stage,
                args=(
                    target,
                    in_channels,
                    out_channels,
                    consts_args,
                    consts_kwargs,
                    state["stop"],
                    f"{actor_id[:8]}.{method}",
                ),
                name=f"dag-{dag_id[:8]}-{method}",
                daemon=True,
            )
            state["threads"].append((t, prog_channels))
            t.start()
        return {"status": "ok"}

    def _h_dag_teardown(self, req: dict) -> dict:
        state = self._dag_programs.pop(req["dag_id"], None)
        if state is not None:
            state["stop"].set()
            for t, channels in state["threads"]:
                t.join(timeout=2.0)
                if t.is_alive():
                    # a stage is still mid-method: closing (munmapping) its
                    # rings under it would segfault the whole worker — leave
                    # them mapped; the thread exits on its next stop-flag
                    # check and the mappings die with it
                    logger.warning(
                        "dag %s stage %s still running at teardown; "
                        "leaving its channels mapped",
                        req["dag_id"][:8],
                        t.name,
                    )
                    continue
                for ch in channels:
                    try:
                        ch.close()
                    except Exception:  # noqa: BLE001
                        pass
        return {"status": "ok"}

    def _h_pipeline_install(self, req: dict) -> dict:
        """Install AOT-compiled pipeline stages into this worker
        (dag/pipeline.py): per stage, open its pre-created in/out rings
        and start the bytes-level stage loop. Stage functions arrive as
        cloudpickle blobs ONCE at install; method stages bind the hosted
        actor instance under the per-actor DAG lock (compiled-DAG calls,
        pipeline calls, and normal pushed methods stay serialized)."""
        from ray_tpu.dag.channel import ShmChannel
        from ray_tpu.dag.pipeline import run_pipeline_stage

        actor_id = req["actor_id"]
        pipe_id = req["pipe_id"]
        instance = self._actors[actor_id]
        entry = self._actor_loops.get(actor_id)
        dag_lock = self._dag_actor_locks.setdefault(actor_id, threading.Lock())
        state = self._pipelines.setdefault(
            pipe_id, {"stop": threading.Event(), "threads": []}
        )
        for prog in req["programs"]:
            in_ch = ShmChannel(prog["in_path"], capacity=prog["capacity"])
            out_ch = ShmChannel(prog["out_path"], capacity=prog["capacity"])
            if prog.get("fn_blob") is not None:
                fn = cloudpickle.loads(prog["fn_blob"])

                def target(x, _fn=fn):
                    return _fn(x)

                name = getattr(fn, "__name__", "fn")
            else:
                method = prog["method"]
                bound = getattr(instance, method)
                if entry is not None:
                    import asyncio

                    loop, _sems = entry

                    async def _awrap(aw):
                        return await aw

                    def target(x, _fn=bound, _loop=loop):
                        from ray_tpu.core.object_store import should_await

                        with dag_lock:
                            out = _fn(x)
                        if should_await(out):
                            return asyncio.run_coroutine_threadsafe(
                                _awrap(out), _loop
                            ).result()
                        return out

                else:

                    def target(x, _fn=bound):
                        with dag_lock:
                            return _fn(x)

                name = method
            t = threading.Thread(
                target=run_pipeline_stage,
                args=(
                    target,
                    in_ch,
                    out_ch,
                    state["stop"],
                    f"{actor_id[:8]}.{name}[{prog['stage']}]",
                ),
                name=f"pipe-{pipe_id[:8]}-s{prog['stage']}",
                daemon=True,
            )
            state["threads"].append((t, [in_ch, out_ch]))
            t.start()
        return {"status": "ok"}

    def _h_pipeline_teardown(self, req: dict) -> dict:
        state = self._pipelines.pop(req["pipe_id"], None)
        if state is not None:
            state["stop"].set()
            for t, channels in state["threads"]:
                t.join(timeout=2.0)
                if t.is_alive():
                    # mid-method stage: munmapping its rings under it
                    # would segfault the worker — leave them mapped, the
                    # thread exits on its next stop-flag check
                    logger.warning(
                        "pipeline %s stage %s still running at teardown; "
                        "leaving its channels mapped",
                        req["pipe_id"][:8],
                        t.name,
                    )
                    continue
                for ch in channels:
                    try:
                        ch.close()
                    except Exception:  # noqa: BLE001
                        pass
        return {"status": "ok"}

    def _h_kill_actor(self, req: dict) -> None:
        self._actors.pop(req["actor_id"], None)
        entry = self._actor_loops.pop(req["actor_id"], None)
        if entry is not None:
            loop, _ = entry
            self._stopping_loops.add(id(loop))

            def begin_shutdown() -> None:
                import asyncio

                async def drain_and_stop() -> None:
                    # cancel in-flight methods and WAIT for the cancellations
                    # to land: their futures resolve with CancelledError →
                    # TaskDone(error) → callers unblock, instead of freezing
                    # forever on a stopped loop. Repeat until quiescent:
                    # a queued create_all can add tasks after one sweep.
                    me = asyncio.current_task()
                    for _ in range(10):
                        tasks = [
                            t for t in asyncio.all_tasks() if t is not me
                        ]
                        if not tasks:
                            break
                        for t in tasks:
                            t.cancel()
                        await asyncio.gather(*tasks, return_exceptions=True)
                    loop.stop()

                loop.create_task(drain_and_stop())

            try:
                loop.call_soon_threadsafe(begin_shutdown)
            except RuntimeError:
                pass

    # C-extension packages whose re-import after a sys.modules purge is
    # undefined (numpy refuses outright); an actor that pulled one in past
    # the baseline makes this process unscrubbabe — refuse, and the agent
    # re-forks a pristine worker instead (ms-scale via the zygote).
    SCRUB_RISKY_ROOTS = frozenset(
        {"jax", "jaxlib", "numpy", "scipy", "pandas", "torch",
         "tensorflow", "grpc", "pyarrow"}
    )

    # threads the framework itself starts lazily after registration —
    # these exit on their own or serve the next actor; anything else
    # alive past the baseline refuses the scrub
    SCRUB_THREAD_OK = (
        "direct-",          # per-actor FIFO executors (self-exiting)
        "task-done",        # done-pool workers
        "task-batch",       # batch-pool workers
        "ThreadPoolExecutor",  # grpc server / stdlib pool workers
        "asyncio_",         # asyncio default-executor workers
    )
    # NOTE: "actor-loop-" is intentionally absent — those threads are
    # JOINED during the scrub, so a survivor (loop that refused to drain)
    # lands in the stray list and refuses the reuse.

    def _h_scrub_actor(self, req: dict) -> dict:
        """Reset this worker to its registration-time state after its
        actor exited cleanly, so the agent can return it to the idle pool
        (worker_pool.cc idle-worker reuse; the reference only reuses TASK
        workers — the scrub contract is what makes actor reuse sound
        here). Refuses (ok=False) whenever pristine state cannot be
        restored; the caller then kills + re-forks instead."""
        aid = req["actor_id"]
        self._h_kill_actor({"actor_id": aid})
        reasons = []
        if self._dag_programs:
            reasons.append("compiled-DAG programs still installed")
        if self._pipelines:
            reasons.append("compiled-pipeline programs still installed")
        if self._actors:
            reasons.append("other actors resident")
        # thread hygiene: the killed actor's event loop drains async
        # (KillActor cancels + stops it via call_soon_threadsafe) — wait
        # for those loop threads to actually exit, then refuse if any
        # OTHER non-framework thread born after registration survives:
        # a user daemon thread is live actor state no scrub can undo.
        for t in threading.enumerate():
            if (
                t not in self._baseline_threads
                and t.name.startswith("actor-loop-")
            ):
                t.join(timeout=5.0)
        stray = sorted(
            t.name
            for t in threading.enumerate()
            if t.is_alive()
            and t is not threading.current_thread()
            and t not in self._baseline_threads
            and not t.name.startswith(self.SCRUB_THREAD_OK)
        )
        if stray:
            reasons.append(f"non-framework threads alive: {','.join(stray[:3])}")
        # module-state reset, scoped to WHOLLY NEW package roots (user
        # code shipped/imported by the actor): those are dropped so the
        # next actor re-imports a fresh copy and mutated module globals
        # cannot leak across reuses. Lazily-loaded SUBmodules of packages
        # already present at registration (grpc/cloudpickle/asyncio
        # internals the framework touches on demand) and stdlib roots are
        # kept — purging them would break the live framework, and actor
        # code does not own their state.
        stdlib = getattr(sys, "stdlib_module_names", ())
        baseline_roots = {m.split(".", 1)[0] for m in self._baseline_modules}
        new_mods = [
            m for m in list(sys.modules) if m not in self._baseline_modules
        ]
        fresh_roots = (
            {m.split(".", 1)[0] for m in new_mods}
            - baseline_roots
            - set(stdlib)
        )
        risky = sorted(fresh_roots & self.SCRUB_RISKY_ROOTS)
        if risky:
            reasons.append(f"unreloadable modules imported: {','.join(risky)}")
        if reasons:
            return {"ok": False, "reason": "; ".join(reasons)}
        purge = [m for m in new_mods if m.split(".", 1)[0] in fresh_roots]
        for m in purge:
            sys.modules.pop(m, None)
        if purge:
            importlib.invalidate_caches()
        # sys.path restore: user code that inserted its own entries
        # (working-dir style) must not leak import resolution into the
        # next actor
        if sys.path != self._baseline_sys_path:
            sys.path[:] = self._baseline_sys_path
            importlib.invalidate_caches()
        # env + cwd restore (covers persisted actor accel env and any
        # os.environ writes by user code)
        for k in list(os.environ):
            if k not in self._baseline_env:
                del os.environ[k]
        for k, v in self._baseline_env.items():
            if os.environ.get(k) != v:
                os.environ[k] = v
        if self._baseline_cwd is not None:
            try:
                if os.getcwd() != self._baseline_cwd:
                    os.chdir(self._baseline_cwd)
            except OSError:
                return {"ok": False, "reason": "cwd unrestorable"}
        self._fn_cache.clear()
        self._fn_cache_order.clear()
        self._dag_actor_locks.pop(aid, None)
        with self._direct_fifo_cv:
            self._direct_fifo.pop(aid, None)
            self._direct_fifo_cv.notify_all()
        with self._env_cv:
            # a persisted actor runtime_env's discarded undo left the gate
            # signature dangling; reuse starts clean
            if self._env_active == 0:
                self._env_sig = None
                self._env_undo = lambda: None
        return {"ok": True}

    def serve_forever(self) -> None:
        while True:
            time.sleep(1.0)
            if os.getppid() == 1:  # agent died; don't linger
                os._exit(0)


def run_worker(agent_address: str, worker_id: str, store_path: str) -> None:
    """Process entry shared by the cold spawn path (``main``) and the
    zygote fork path (``zygote._child_main``): platform pin, diagnostics
    hooks, then the Worker loop. Never returns."""
    # An inherited JAX_PLATFORMS env var must be enforced via jax.config:
    # accelerator plugin hooks (e.g. the axon TPU tunnel) can initialize
    # their backend during ANY jax call regardless of the env var, and a
    # wedged transport then hangs the worker's first user jax call forever.
    # config.update pins the platform set before any backend comes up.
    # (Idempotent for forked workers: the zygote already pinned it.)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax

            jax.config.update("jax_platforms", plat)
        except Exception:  # noqa: BLE001 - jax optional for pure-CPU tasks
            pass
    logging.basicConfig(level=logging.WARNING)
    # stuck-worker diagnosis: `kill -USR1 <pid>` dumps all thread stacks
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1, all_threads=True)
    worker = Worker(agent_address, worker_id, store_path)
    prof_dir = os.environ.get("RAY_TPU_PROFILE_WORKER")
    if prof_dir:
        # perf diagnosis: dump per-worker cProfile stats on SIGUSR2
        import cProfile
        import signal as _sig

        _pr = cProfile.Profile()
        _pr.enable()

        def _dump(_sig_no, _frm):
            _pr.dump_stats(
                os.path.join(prof_dir, f"worker-{worker_id}.prof")
            )

        _sig.signal(_sig.SIGUSR2, _dump)
    worker.serve_forever()


def seal_local_value(value: Any, owner: str = "") -> Optional[str]:
    """Arena-direct object seal from INSIDE a cluster worker: one
    pickle-5 gather into the node's shm arena (PR 13's ndarray seal
    path — numpy leaves scatter-write as out-of-band frames), the
    SealInfo rides the worker's existing direct-seal batch to the agent
    and from there to the head's object directory. ``owner`` (a driver
    client id) is registered as the holder, so the object fate-shares
    with that driver and stays alive until it frees the generation.

    Returns the new object's hex id, or None when not running inside a
    cluster worker (callers fall back to ``ray_tpu.put``). Used by the
    elastic-training state plane to seal param/optimizer shards without
    a head RPC on the data path.
    """
    import dataclasses as _dc

    w = _CURRENT_WORKER
    if w is None or w.store is None:
        return None
    from ray_tpu._ids import rand_hex

    hex_id = rand_hex(14)
    seal = w.put_value(hex_id, value)
    if owner:
        seal = _dc.replace(seal, owner=owner)
    with w._direct_seal_cv:
        w._direct_seals.append(seal)
        w._direct_seal_cv.notify_all()
    return hex_id


def fetch_into_local_arena(
    hex_id: str, timeout: float = 60.0, land: str = "device"
) -> Any:
    """Pull ``hex_id`` through THIS worker's agent so a copy lands in
    the local arena and the head directory gains a second location
    (buddy replication for elastic state shards; the pull itself rides
    the socket plane / chunked fallback like any located fetch).
    Returns the deserialized value. Raises when not inside a worker.

    ``land`` picks the device-frame landing mode for the deserialize:
    ``"device"`` (default) lands jax leaves back on device with one
    ``device_put`` straight from the arena view — no intermediate host
    copy; ``"host"`` returns read-only host views (callers that only
    re-export, e.g. buddy replication without consumption)."""
    w = _CURRENT_WORKER
    if w is None:
        raise RuntimeError("fetch_into_local_arena: not inside a worker")
    from ray_tpu.cluster.device_plane import landing

    with landing(land):
        return w.get_object(hex_id, timeout=timeout)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--agent", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--store", default="")
    args = parser.parse_args()
    pip_dir = os.environ.get("RAY_TPU_PIP_ENV_DIR")
    if pip_dir:
        # pip runtime env: the agent built this --target dir for the env
        # this worker serves; it shadows base site-packages (pip_env.py).
        # Cold-spawn only — env workers never fork from the zygote (its
        # sys.path/modules are already bound to base site-packages).
        sys.path.insert(0, pip_dir)
    run_worker(args.agent, args.worker_id, args.store)


if __name__ == "__main__":
    main()
