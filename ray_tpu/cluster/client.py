"""Driver-side client runtime for a distributed ray_tpu cluster.

``ray_tpu.init(address="host:port")`` swaps the in-process Runtime for a
``RemoteRuntime`` — the same duck-typed surface the public API calls
(submit / put_object / get_object / wait / actors / PGs), but every
operation is an RPC to the head or a node agent. This is the moral
equivalent of the reference driver's CoreWorker connecting to the GCS
and raylets (/root/reference/python/ray/_private/worker.py:1406), and it
doubles as the Ray-Client analog (util/client/) since a driver can be
anywhere with connectivity to the cluster.
"""
from __future__ import annotations

import hashlib
import inspect
import os
import pickle
import sys
import threading
import time
import logging
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

logger = logging.getLogger("ray_tpu.cluster.client")

from ray_tpu.core.object_store import GetTimeoutError, ObjectRef
from ray_tpu.core.runtime import TaskSpec

from . import serialization as wire
from .common import INLINE_OBJECT_MAX, LeaseRequest, new_id
from .rpc import RpcClient, RpcDeadlineError, RpcError, RpcServer

_BY_VALUE_REGISTERED: set = set()


def _ship_module_by_value(obj: Any) -> None:
    """User code living outside site-packages (driver scripts, test files)
    isn't importable on workers — pickle its module by value (the reference
    ships the function definition in the task spec the same way)."""
    try:
        mod = inspect.getmodule(obj)
        if mod is None:
            return
        name = getattr(mod, "__name__", "")
        if name in _BY_VALUE_REGISTERED or name == "__main__":
            if name == "__main__":
                return  # cloudpickle already serializes __main__ by value
            return
        f = getattr(mod, "__file__", None)
        if not f:
            return
        if (
            "site-packages" in f
            or "/ray_tpu/" in f
            or f.startswith(sys.prefix)
            or f.startswith(getattr(sys, "base_prefix", sys.prefix))
        ):
            return
        cloudpickle.register_pickle_by_value(mod)
        _BY_VALUE_REGISTERED.add(name)
    except Exception:  # noqa: BLE001 - best-effort
        pass


class _RemoteStore:
    """ray.wait support against the head's object directory."""

    def __init__(self, runtime: "RemoteRuntime"):
        self._rt = runtime

    def wait_many(
        self,
        refs: List[ObjectRef],
        num_returns: int,
        timeout: Optional[float],
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        """One multiplexed server-side long-poll per window: the head
        blocks until num_returns ids resolve (WaitObjectBatch num_returns),
        so readiness propagates at RPC latency without client sleep
        loops."""
        from ray_tpu.config import cfg

        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectRef] = []
        pending = list(refs)
        t_start = time.monotonic()
        while pending and len(ready) < num_returns:
            # direct-call results resolve locally without a head round trip
            if self._rt._direct_enabled:
                still: List[ObjectRef] = []
                for r in pending:
                    if (
                        len(ready) < num_returns
                        and r.hex in self._rt._direct_results
                    ):
                        ready.append(r)
                    else:
                        still.append(r)
                pending = still
                if not pending or len(ready) >= num_returns:
                    break
                # every remaining ref is a direct call whose result will
                # arrive by push: park on the push channel's condition
                # variable instead of a head long-poll — a WaitObjectBatch
                # RPC would sit blind for its whole window while pushes
                # land locally. After the fallback grace (push may have
                # been lost) the head path takes over.
                if (
                    all(h.hex in self._rt._direct_pending for h in pending)
                    and time.monotonic() - t_start
                    < self._rt._direct_wait_fallback_s
                ):
                    wait_s = 0.2
                    if deadline is not None:
                        wait_s = min(
                            wait_s, max(0.0, deadline - time.monotonic())
                        )
                    with self._rt._direct_cv:
                        if not any(
                            h.hex in self._rt._direct_results
                            for h in pending
                        ):
                            self._rt._direct_cv.wait(timeout=wait_s)
                    if (
                        deadline is not None
                        and time.monotonic() >= deadline
                    ):
                        break
                    continue
            window = 5.0
            if deadline is not None:
                window = min(window, max(0.0, deadline - time.monotonic()))
            replies = self._rt.head.call(
                "WaitObjectBatch",
                {
                    "object_ids": [r.hex for r in pending],
                    "timeout": window,
                    "num_returns": max(1, num_returns - len(ready)),
                },
                timeout=window + 15.0,
            )
            still = []
            for r, rep in zip(pending, replies):
                if len(ready) < num_returns and rep["status"] != "pending":
                    ready.append(r)
                else:
                    still.append(r)
            pending = still
            if deadline is not None and time.monotonic() >= deadline:
                break
        return ready, pending


class RemotePlacementGroup:
    """Driver-side PG handle for cluster mode (util/placement_group.py
    analog); picklable — it carries only ids/specs."""

    def __init__(self, pg_id: str, bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    def wait(self, timeout_seconds: float = 30) -> bool:
        from ray_tpu.core.runtime import get_runtime

        try:
            get_runtime().wait_placement_group(self.id, timeout=timeout_seconds)
            return True
        except TimeoutError:
            return False

    def __repr__(self) -> str:
        return f"RemotePlacementGroup({self.id[:8]}, {self.strategy})"


class _DirectActorChannel:
    """Caller-side direct submission channel to one actor's worker process
    (reference: ActorTaskSubmitter's per-actor ordered send queue,
    core_worker/task_submission/actor_task_submitter.h:79). Methods are
    coalesced into DirectPushBatch RPCs straight to the worker; results
    come back via the runtime's callback server. The head never sees the
    hot path — it only receives coalesced seal reports for the object
    directory. On any transport failure the channel drains its queue back
    through the head-scheduled lease path (which owns restart semantics);
    a batch that died mid-flight may re-execute (at-least-once, like the
    reference's actor task retries)."""

    MAX_BATCH = 256

    def __init__(self, runtime: "RemoteRuntime", actor_id: str):
        self._rt = runtime
        self.actor_id = actor_id
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._dead = False
        self._accepted: Dict[str, dict] = {}  # ref hex -> item (unresolved)
        self._worker: Optional[RpcClient] = None
        self._thread = threading.Thread(
            target=self._loop, name=f"direct-{actor_id[:6]}", daemon=True
        )
        self._thread.start()

    def submit(self, item: dict) -> None:
        with self._cv:
            if not self._dead:
                self._q.append(item)
                self._cv.notify()
                return
        # fallback OUTSIDE self._cv: _fallback_submit takes the runtime's
        # _direct_cv, and _h_direct_results holds _direct_cv while calling
        # on_result — nesting here would be an AB-BA deadlock
        self._rt._fallback_submit(item)

    def submit_many(self, items: List[dict]) -> None:
        """Window submission: one lock pass + one sender wakeup for a
        whole batch of calls (the Data executor dispatches per-actor
        block windows through here — per-item notify overhead was a
        measurable slice of the 50k-block submit path)."""
        with self._cv:
            if not self._dead:
                self._q.extend(items)
                self._cv.notify()
                return
        for item in items:
            self._rt._fallback_submit(item)

    def on_result(self, ref_hex: str) -> None:
        # single GIL-atomic pop; deliberately lock-free (callers hold the
        # runtime's _direct_cv — see submit() ordering note)
        self._accepted.pop(ref_hex, None)

    def _resolve_worker(self) -> Optional[RpcClient]:
        handle = RemoteActorHandle(self._rt, self.actor_id, object)
        info = self._rt.wait_actor_alive(handle, timeout=60.0)
        agent = self._rt._agent(info.node_id, info.address)
        reply = agent.call(
            "ActorWorkerAddress", {"actor_id": self.actor_id}, timeout=10.0
        )
        return RpcClient(reply["address"])

    def _loop(self) -> None:
        import logging

        log = logging.getLogger("ray_tpu.cluster.client")
        try:
            self._worker = self._resolve_worker()
        except BaseException as exc:  # noqa: BLE001
            log.info(
                "direct channel to %s unavailable (%r); using head path",
                self.actor_id[:8],
                exc,
            )
            self._fail_over()
            return
        idle_checks = 0.0
        while True:
            with self._cv:
                while not self._q and not self._dead:
                    self._cv.wait(timeout=1.0)
                    # watchdog: accepted-but-unresolved items + silent
                    # worker means the worker may have died mid-call
                    if self._accepted and not self._q:
                        idle_checks += 1.0
                        if idle_checks >= 2.0:
                            break
                if self._dead:
                    return
                batch = []
                while self._q and len(batch) < self.MAX_BATCH:
                    batch.append(self._q.popleft())
                if batch:
                    for it in batch:
                        self._accepted[it["ref"]] = it
            try:
                if batch:
                    # strip client-local fields (e.g. the live arg refs kept
                    # to pin args until completion) from the wire items
                    wire = [
                        {k: v for k, v in it.items() if not k.startswith("_")}
                        for it in batch
                    ]
                    accepts = self._worker.call(
                        "DirectPushBatch",
                        {
                            "client_addr": self._rt._callback_address(),
                            "items": wire,
                        },
                        timeout=60.0,
                    )
                    done = []
                    for it, status in zip(batch, accepts):
                        if isinstance(status, dict):
                            # fast path: the result rode the accept reply
                            done.append(status["done"])
                        elif status != "accepted":
                            with self._cv:
                                self._accepted.pop(it["ref"], None)
                            self._rt._fallback_submit(it)
                    if done:
                        self._rt._h_direct_results(done)
                else:
                    # idle probe of a worker that owes us results
                    self._worker.call("Ping", timeout=5.0)
                    idle_checks = 0.0
            except RpcError:
                self._fail_over(batch)
                return

    def _fail_over(self, batch: Optional[list] = None) -> None:
        """Worker unreachable: everything unresolved re-routes through the
        head, which knows whether the actor is restarting or dead."""
        with self._cv:
            self._dead = True
            items = list(self._accepted.values())
            self._accepted.clear()
            queued = list(self._q)
            self._q.clear()
        seen = set()
        for it in (batch or []) + items + queued:
            if it["ref"] not in seen:
                seen.add(it["ref"])
                self._rt._fallback_submit(it)
        self._rt._drop_direct_channel(self.actor_id, self)

    def stop(self) -> None:
        with self._cv:
            self._dead = True
            self._cv.notify_all()


class RemoteActorHandle:
    def __init__(self, runtime: "RemoteRuntime", actor_id: str, cls: type):
        self._runtime = runtime
        self._actor_id = actor_id
        self._cls = cls

    def __getattr__(self, name: str):
        # "__call__" is a legitimate remote method (serve deployments
        # dispatch it); every other underscore name stays an attribute
        # error so pickling/introspection behave
        if name.startswith("_") and name != "__call__":
            raise AttributeError(name)
        return _RemoteMethod(self._runtime, self._actor_id, name)

    def __reduce__(self):
        return (_rebuild_actor_handle, (self._actor_id, self._cls))


def _rebuild_actor_handle(actor_id: str, cls: type):
    from ray_tpu.core.runtime import get_runtime

    return RemoteActorHandle(get_runtime(), actor_id, cls)


class _RemoteMethod:
    def __init__(
        self,
        runtime: "RemoteRuntime",
        actor_id: str,
        method: str,
        num_returns=1,
    ):
        self._runtime = runtime
        self._actor_id = actor_id
        self._method = method
        self._num_returns = num_returns

    def options(self, num_returns=None, **_ignored) -> "_RemoteMethod":
        return _RemoteMethod(
            self._runtime,
            self._actor_id,
            self._method,
            num_returns or self._num_returns,
        )

    def remote(self, *args, **kwargs):
        if self._num_returns == "streaming":
            return self._runtime.submit_actor_method_streaming(
                self._actor_id, self._method, args, kwargs
            )
        return self._runtime.submit_actor_method(
            self._actor_id, self._method, args, kwargs
        )


class _PipelinedSender:
    """Client→head submission pipeline (the reference's task-submission
    pipelining, core_worker/task_submission/normal_task_submitter.h): lease
    submissions and refcount updates ride ONE ordered queue, coalesced into
    ``ClientBatch`` RPCs. An idle sender ships immediately (no added
    latency); under load everything queued while the previous RPC was in
    flight merges into one message. Ordering between a submission that
    registers return-id holders and a later release of those ids is
    preserved by construction."""

    MAX_BATCH = 512

    def __init__(self, client: RpcClient):
        self._client = client
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._enqueued = 0
        self._acked = 0
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name="lease-pipeline", daemon=True
        )
        self._thread.start()

    def enqueue(
        self,
        kind: str,
        payload: Any,
        wait: bool = False,
        wait_timeout: Optional[float] = None,
    ) -> None:
        """Queue one control item. ``wait=True`` blocks until the head has
        processed it; ``wait_timeout`` bounds that wait — on expiry an
        RpcError raises (the item STAYS queued and delivers when the head
        returns; only this caller's synchronous view gives up)."""
        with self._cv:
            if self._stop:
                return
            self._q.append((kind, payload))
            self._enqueued += 1
            ticket = self._enqueued
            self._cv.notify_all()
        if wait:
            deadline = (
                None
                if wait_timeout is None
                else time.monotonic() + wait_timeout
            )
            with self._cv:
                while self._acked < ticket and not self._stop:
                    if (
                        deadline is not None
                        and time.monotonic() >= deadline
                    ):
                        raise RpcError(
                            f"head unreachable: {kind} not acknowledged "
                            f"within {wait_timeout}s (still queued)"
                        )
                    self._cv.wait(timeout=0.5)

    def enqueue_many(self, kind: str, payloads: List[Any]) -> None:
        """Queue a window of same-kind control items under one lock pass
        (ordered with everything else on the pipeline)."""
        with self._cv:
            if self._stop:
                return
            for p in payloads:
                self._q.append((kind, p))
            self._enqueued += len(payloads)
            self._cv.notify_all()

    def _loop(self) -> None:
        import logging

        log = logging.getLogger("ray_tpu.cluster.client")
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(timeout=0.5)
                if not self._q:
                    if self._stop:
                        return
                    continue
                n = min(len(self._q), self.MAX_BATCH)
                batch = [self._q.popleft() for _ in range(n)]
            delivered = False
            attempts = 0
            while not delivered:
                try:
                    attempts += 1
                    if attempts == 2 or attempts % 60 == 0:
                        log.warning(
                            "ClientBatch re-send #%d (%d items)",
                            attempts,
                            len(batch),
                        )
                    self._client.call(
                        "ClientBatch",
                        batch,
                        timeout=60.0,
                        retries=8,
                        retry_interval=0.25,
                    )
                    delivered = True
                except (RpcError, RuntimeError):
                    # a dropped lease would strand its caller's get()
                    # forever and a dropped release leaks the object —
                    # keep the batch and retry until the head comes back
                    # (or this runtime shuts down). RuntimeError: the
                    # channel's executor closed under us (shutdown race) —
                    # same stop checks apply, never an unhandled thread
                    # exception.
                    import sys

                    if sys.is_finalizing():
                        return  # interpreter exit: nobody to deliver for
                    if attempts <= 2 or attempts % 60 == 0:
                        log.warning(
                            "head unreachable; retrying %d control items",
                            len(batch),
                        )
                    # event-driven pause (the long-poll pattern the rest
                    # of the client uses, e.g. wait_many): park on the
                    # queue's condition variable so a stop() — or new
                    # work signalling the head may be back — wakes the
                    # retry immediately instead of sleeping blind.
                    with self._cv:
                        if self._stop:
                            return
                        self._cv.wait(timeout=0.5)
            with self._cv:
                self._acked += len(batch)
                self._cv.notify_all()

    def drain(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        with self._cv:
            target = self._enqueued
            while self._acked < target and time.monotonic() < deadline:
                self._cv.wait(timeout=0.2)

    def stop(self) -> None:
        self.drain()
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        # join BEFORE the caller closes the rpc channel: an in-flight send
        # racing the channel's executor shutdown was the
        # cannot-schedule-new-futures stray-thread exception the full
        # suite used to end with
        self._thread.join(timeout=5.0)


class RemoteRuntime:
    """Duck-typed Runtime whose backend is a live cluster."""

    is_remote = True

    def __init__(self, address: str, runtime_env: Optional[dict] = None):
        self.address = address
        self.head = RpcClient(address)
        self.head.call("Ping", timeout=10.0, retries=20, retry_interval=0.25)
        self.runtime_env = runtime_env
        self._agents: Dict[str, RpcClient] = {}
        self._lock = threading.Lock()
        self.store = _RemoteStore(self)
        self.metrics: Dict[str, int] = {}
        # distributed refcounting: this process's holder identity + release
        # reporter. Inside a cluster worker the worker's flusher (which
        # routes via its agent) is already installed and is reused.
        from ray_tpu.core import refcount

        self.client_id = refcount.get_holder_id()
        # direct actor calls: per-actor submission channels straight to the
        # hosting worker; results arrive on a lazily-started callback
        # server. RAY_TPU_DIRECT_ACTOR_CALLS=0 forces everything through
        # the head-scheduled lease path.
        from ray_tpu.config import cfg

        self._direct_enabled = cfg.direct_actor_calls
        # hot-path cfg snapshot: these flags are read per submission /
        # per awaited ref, and cfg reads consult os.environ live. Set the
        # env before connect() to change them for a runtime.
        self._trace_autostart = cfg.trace_tasks
        self._direct_wait_fallback_s = cfg.direct_wait_fallback_s
        # one cloudpickle of each task function per function OBJECT (weak:
        # dead lambdas drop their blobs); see _serialize_fn
        import weakref

        self._fn_blobs: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        self._direct_channels: Dict[str, _DirectActorChannel] = {}
        self._direct_results: Dict[str, tuple] = {}  # hex -> (kind, payload)
        # FIFO bound on the local result cache: fire-and-forget callers
        # never get() their refs, and every result also reached the head's
        # directory — evicted entries just resolve through the head
        self._direct_results_order: deque = deque()
        self._direct_results_cap = cfg.direct_results_cap
        self._direct_pending: Dict[str, str] = {}  # hex -> actor_id
        # streaming generators: task_id -> (base_index, [item ids], done)
        self._stream_cache: Dict[str, tuple] = {}
        self._direct_arg_pins: Dict[str, List[str]] = {}  # hex -> arg ids
        # owner-held results (cfg.direct_deferred_seals): hex -> contained
        # ids; the head learns about these objects only on share/evict
        self._deferred_seals: Dict[str, List[str]] = {}
        # refs shared into another submission BEFORE their direct result
        # arrived: the arrival handler uploads these instead of deferring
        self._shared_pending: set = set()
        self._direct_cv = threading.Condition()
        self._callback_server: Optional[RpcServer] = None
        # dedicated channel for the pipeline: its traffic during a head
        # outage must not push the main channel into gRPC reconnect backoff
        self._pipe_chan = RpcClient(address)
        self._sender = _PipelinedSender(self._pipe_chan)
        incumbent = refcount.current_consumer()
        if isinstance(incumbent, refcount.RefFlusher):
            self._flusher = incumbent
            self._owns_flusher = False
        else:
            self._flusher = refcount.RefFlusher(
                lambda inc, dec: self._sender.enqueue(
                    "ref",
                    {"holder": self.client_id, "increfs": inc, "decrefs": dec},
                    wait=True,
                ),
                holder=self.client_id,
            )
            refcount.install_consumer(self._flusher)
            self._owns_flusher = True

    def _read(
        self,
        method: str,
        payload: Any = None,
        timeout: float = 30.0,
        deadline_s: Optional[float] = None,
    ):
        """Idempotent head reads retry through transport blips — a client
        rides through a head restart the way the reference's GCS client
        does (gcs_rpc_client.h retry budgets). ``deadline_s`` propagates a
        caller's overall budget: the retry loop never outlives it."""
        return self.head.call(
            method,
            payload,
            timeout=timeout,
            retries=8,
            retry_interval=0.25,
            deadline_s=deadline_s,
        )

    # ------------------------------------------------------------------
    # tasks
    # ------------------------------------------------------------------
    def _serialize_fn(self, fn) -> tuple:
        """Pickle a task function once per function object.

        Returns ``(blob, fn_id, fn_arg_ids, cacheable)``. Cached only when
        serialization collected zero ObjectRefs — a closure over a ref
        keeps per-call (de)serialization so ref lifetimes stay
        per-execution. Matches the reference's one-time function export
        (function_manager) vs. our previous per-call re-pickle: closure
        CELL mutations after first submission are not re-shipped, same as
        the reference."""
        from ray_tpu.core.refcount import collect_serialized

        try:
            ent = self._fn_blobs.get(fn)
        except TypeError:
            ent = None  # unhashable/unweakrefable callable
        if ent is not None:
            return ent
        _ship_module_by_value(fn)
        with collect_serialized() as ids:
            blob = cloudpickle.dumps(fn)
        fn_id = hashlib.blake2b(blob, digest_size=8).hexdigest()
        ent = (blob, fn_id, frozenset(ids), not ids)
        if not ids:
            try:
                self._fn_blobs[fn] = ent
            except TypeError:
                pass
        return ent

    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        from ray_tpu.core.refcount import collect_serialized

        fn_blob, fn_id, fn_arg_ids, fn_cacheable = self._serialize_fn(
            spec.func
        )
        with collect_serialized() as arg_ids:
            payload = wire.dumps((spec.args, spec.kwargs))
        if fn_arg_ids:
            arg_ids |= fn_arg_ids
        deps = [a.hex for a in spec.args if isinstance(a, ObjectRef)]
        deps += [
            v.hex for v in spec.kwargs.values() if isinstance(v, ObjectRef)
        ]
        self._flush_deferred_seals(arg_ids)
        from ray_tpu.util import tracing

        trace = spec.trace or tracing.child_context(
            spec.task_id, self._trace_autostart
        )
        lease = LeaseRequest(
            task_id=spec.task_id,
            name=spec.name,
            payload=payload,
            return_ids=[r.hex for r in spec.returns],
            resources=spec.resources,
            kind="task",
            max_retries=spec.max_retries,
            retry_exceptions=spec.retry_exceptions,
            strategy=spec.strategy,
            runtime_env=(
                {**(self.runtime_env or {}), **spec.runtime_env}
                if spec.runtime_env
                else self.runtime_env
            ),
            arg_ids=sorted(arg_ids),
            deps=deps,
            client_id=self.client_id,
            trace=trace,
            fn_blob=fn_blob,
            fn_id=fn_id,
            fn_cache=fn_cacheable,
            streaming=bool(getattr(spec, "streaming", False)),
        )
        self._sender.enqueue("lease", lease)
        self._flusher.note_registered(lease.return_ids)
        return spec.returns

    def stream_next(
        self, task_id: str, index: int, timeout: Optional[float]
    ) -> Optional[ObjectRef]:
        """Long-poll the head for item ``index`` of a streaming-generator
        task (ObjectRefGenerator backend). None = stream ended before it.
        The ``after`` watermark doubles as the consumption ack that frees
        the executor's backpressure window."""
        cached = self._stream_cache.get(task_id)
        if cached is not None:
            base, ids, done = cached
            k = index - base
            if 0 <= k < len(ids):
                return ObjectRef(ids[k], owner=self.client_id)
            if done and k >= len(ids):
                self._stream_cache.pop(task_id, None)
                return None
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while True:
            wait_s = 2.0
            if deadline is not None:
                wait_s = min(wait_s, deadline - time.monotonic())
                if wait_s <= 0:
                    raise GetTimeoutError(
                        f"stream {task_id} item {index} not ready"
                    )
            reply = self._read(
                "WaitStream",
                {
                    "task_id": task_id,
                    "after": index,
                    "timeout": wait_s,
                    "holder": self.client_id,
                },
                timeout=wait_s + 15.0,
            )
            items = reply.get("items") or []
            done = bool(reply.get("done"))
            if items:
                # one long-poll returns every ready item; serve the rest
                # of the burst from this cache instead of an RPC per item.
                # Bounded: abandoned generators clear their entry via
                # stream_abandon; the cap catches pathological churn.
                if len(self._stream_cache) > 256:
                    self._stream_cache.pop(
                        next(iter(self._stream_cache)), None
                    )
                self._stream_cache[task_id] = (index, items, done)
                return ObjectRef(items[0], owner=self.client_id)
            if done:
                self._stream_cache.pop(task_id, None)
                return None

    def stream_abandon(self, task_id: str) -> None:
        """Best-effort consumer-drop notice (ObjectRefGenerator.__del__)."""
        self._stream_cache.pop(task_id, None)
        try:
            self.head.call("StreamAbandon", {"task_id": task_id}, timeout=5.0)
        except RpcError:
            pass

    def submit_actor_method(
        self, actor_id: str, method: str, args: tuple, kwargs: dict
    ) -> ObjectRef:
        # a batch of one: submit_actor_method_batch owns the single
        # implementation of item/lease construction and arg pinning
        return self.submit_actor_method_batch(
            actor_id, method, [(args, kwargs)]
        )[0]

    def submit_actor_method_batch(
        self, actor_id: str, method: str, calls: List[tuple]
    ) -> List[ObjectRef]:
        """Submit a WINDOW of calls to one actor in one pass: one
        pin/bookkeeping lock acquisition and one channel (or pipeline)
        wakeup for the whole batch — the ordered batch path PR 2 gave to
        actor creations/kills, extended to actor-task submission. The
        Data executor's actor pools dispatch per-actor block windows
        through this instead of per-block ``submit_actor_method``.

        ``calls`` is a sequence of ``(args, kwargs)``; returns one
        ObjectRef per call, in order.
        """
        from ray_tpu.core.refcount import TRACKER, collect_serialized

        from ray_tpu.util import tracing

        refs: List[ObjectRef] = []
        prepared: List[tuple] = []  # (ref, ids, item) | (ref, lease)
        for args, kwargs in calls:
            ref = ObjectRef.new(owner=actor_id)
            with collect_serialized() as arg_ids:
                payload = wire.dumps((method, args, kwargs))
            if arg_ids:
                self._flush_deferred_seals(arg_ids)
            ids = sorted(arg_ids)
            tid = new_id()
            refs.append(ref)
            if self._direct_enabled:
                item = {
                    "task_id": tid,
                    "actor_id": actor_id,
                    "ref": ref.hex,
                    "payload": payload,
                    "client_id": self.client_id,
                    "name": f"{actor_id[:8]}.{method}",
                    "arg_ids": ids,
                    "trace": tracing.child_context(
                        tid, self._trace_autostart
                    ),
                }
                prepared.append((ref, ids, item))
            else:
                prepared.append(
                    (
                        ref,
                        LeaseRequest(
                            task_id=tid,
                            name=f"{actor_id[:8]}.{method}",
                            payload=payload,
                            return_ids=[ref.hex],
                            resources={},
                            kind="actor_method",
                            actor_id=actor_id,
                            max_retries=0,
                            arg_ids=ids,
                            client_id=self.client_id,
                        ),
                    )
                )
        if not self._direct_enabled:
            self._flusher.note_registered([r.hex for r in refs])
            self._sender.enqueue_many(
                "lease", [lease for _, lease in prepared]
            )
            return refs
        # pin every arg (incl. refs nested in containers) until the
        # result lands: the worker registers its borrows synchronously
        # before replying, so our later release can never free an object
        # the actor still holds (the lease path gets this from head-side
        # arg pins; the direct path pins at the caller). Pinning happens
        # HERE, after every call in the window serialized successfully —
        # an incref taken per-call inside the prepare loop would leak for
        # calls 0..k-1 when call k's wire.dumps raises (nothing was
        # registered yet, so nothing would ever release them).
        with self._direct_cv:
            for ref, ids, _ in prepared:
                for h in ids:
                    TRACKER.incref(h)
                self._direct_pending[ref.hex] = actor_id
                if ids:
                    self._direct_arg_pins[ref.hex] = ids
        chan = self._direct_channels.get(actor_id)
        if chan is None:
            with self._lock:
                chan = self._direct_channels.get(actor_id)
                if chan is None:
                    chan = _DirectActorChannel(self, actor_id)
                    self._direct_channels[actor_id] = chan
        chan.submit_many([item for _, _, item in prepared])
        return refs

    def _submit_actor_lease(
        self,
        *,
        task_id: str,
        actor_id: str,
        name: str,
        payload: bytes,
        return_id: Optional[str],
        arg_ids: List[str],
        streaming: bool = False,
    ) -> None:
        lease = LeaseRequest(
            task_id=task_id,
            name=name,
            payload=payload,
            return_ids=[return_id] if return_id else [],
            resources={},
            kind="actor_method",
            actor_id=actor_id,
            max_retries=0,
            arg_ids=arg_ids,
            client_id=self.client_id,
            streaming=streaming,
        )
        self._sender.enqueue("lease", lease)

    def submit_actor_method_streaming(
        self, actor_id: str, method: str, args: tuple, kwargs: dict
    ):
        """num_returns="streaming" actor method: always the head-scheduled
        lease path (the direct channel replies once per call; a stream
        needs the per-item seal plumbing), yielding an
        ObjectRefGenerator like a streaming task."""
        from ray_tpu.core.object_store import ObjectRefGenerator
        from ray_tpu.core.refcount import collect_serialized

        with collect_serialized() as arg_ids:
            payload = wire.dumps((method, args, kwargs))
        if arg_ids:
            self._flush_deferred_seals(arg_ids)
        tid = new_id()
        self._submit_actor_lease(
            task_id=tid,
            actor_id=actor_id,
            name=f"{actor_id[:8]}.{method}",
            payload=payload,
            return_id=None,
            arg_ids=sorted(arg_ids),
            streaming=True,
        )
        return ObjectRefGenerator(tid, self)

    # ---- direct-call plumbing ----------------------------------------
    def _callback_address(self) -> str:
        with self._lock:
            if self._callback_server is None:
                self._callback_server = RpcServer(
                    {
                        "DirectResults": self._h_direct_results,
                        "Ping": lambda r: "pong",
                    },
                    port=0,
                    max_workers=4,
                )
            return self._callback_server.address

    def _h_direct_results(self, results: List[dict]) -> None:
        from ray_tpu.core.refcount import TRACKER

        unpin: List[str] = []
        uploads: List[tuple] = []  # evicted owner-held objects → head
        register: List[str] = []  # head-sealed results: holder is on books
        with self._direct_cv:
            for r in results:
                h = r["ref"]
                if "deferred_seal" not in r:
                    # the worker sealed this one to the head (error, big
                    # value, ref-containing result, or deferred seals
                    # off): the seal registered us as holder, so a local
                    # release is owed — and any share-while-pending flag
                    # is moot (the head knows the object)
                    register.append(h)
                    self._shared_pending.discard(h)
                if r["status"] == "ok":
                    self._direct_results[h] = ("val", r["value"])
                    if "deferred_seal" in r:
                        contained = list(r["deferred_seal"] or ())
                        if h in self._shared_pending:
                            # the ref was already shared into another
                            # submission while the call ran: a consumer
                            # somewhere is dep-waiting on the head —
                            # upload now, don't defer
                            self._shared_pending.discard(h)
                            uploads.append((h, r["value"], contained))
                        else:
                            # ownership model: we (the caller) hold the
                            # only record of this object; the head learns
                            # about it on share or eviction
                            self._deferred_seals[h] = contained
                elif r["status"] == "error":
                    self._direct_results[h] = ("err", r["error"])
                else:
                    self._direct_results[h] = ("seal", r["seal"])
                self._direct_results_order.append(h)
                # lazy deque hygiene: drop heads already consumed by get()
                # (so the deque tracks the dict), then evict over cap
                while self._direct_results_order:
                    head = self._direct_results_order[0]
                    if head not in self._direct_results:
                        self._deferred_seals.pop(head, None)
                        self._direct_results_order.popleft()
                    elif len(self._direct_results) > self._direct_results_cap:
                        ev = self._direct_results_order.popleft()
                        entry = self._direct_results.pop(ev, None)
                        contained = self._deferred_seals.pop(ev, None)
                        if (
                            contained is not None
                            and entry is not None
                            and entry[0] == "val"
                            and TRACKER.count(ev) > 0
                        ):
                            # evicting an owner-held object someone still
                            # references: persist it to the head first
                            uploads.append((ev, entry[1], contained))
                    else:
                        break
                # a live never-consumed entry at the front blocks the lazy
                # sweep: periodically compact the deque against the dict
                if len(self._direct_results_order) > 2 * self._direct_results_cap:
                    self._direct_results_order = deque(
                        x
                        for x in self._direct_results_order
                        if x in self._direct_results
                    )
                aid = self._direct_pending.pop(h, None)
                if aid is not None:
                    chan = self._direct_channels.get(aid)
                    if chan is not None:
                        chan.on_result(h)
                unpin.extend(self._direct_arg_pins.pop(h, ()))
            self._direct_cv.notify_all()
        if register:
            self._flusher.note_registered_live(register)
        for ev, data, contained in uploads:
            if not self._upload_owned(ev, data, contained):
                # we are the ONLY copy: losing the record would strand the
                # ref forever — re-cache (over cap; a later sweep retries)
                with self._direct_cv:
                    if ev not in self._direct_results:
                        self._direct_results[ev] = ("val", data)
                        self._direct_results_order.append(ev)
                    self._deferred_seals.setdefault(ev, contained)
        # release the per-call arg pins (the worker's borrow registrations
        # are on the books before its result reaches us)
        for h in unpin:
            TRACKER.decref(h)

    def _upload_owned(self, h: str, data: bytes, contained: List[str]) -> bool:
        """Persist an owner-held direct-call result into the head's object
        table (holder = this client) — called when the ref is shared into
        another submission or evicted from the local cache while still
        referenced. After this the normal head-directory lifecycle owns
        the object. Returns False (and logs) if the head stayed
        unreachable through the retry budget — the caller must keep its
        record so a later share can try again."""
        try:
            self.head.call(
                "PutObject",
                {
                    "object_id": h,
                    "data": data,
                    "holder": self.client_id,
                    "contained_ids": sorted(contained),
                },
                retries=8,
                retry_interval=0.25,
            )
            self._flusher.note_registered_live([h])
            return True
        except Exception:  # noqa: BLE001 - head gone; value stays local
            logger.warning("owner-held object upload failed", exc_info=True)
            return False

    def _flush_deferred_seals(self, ids) -> None:
        """Before a submission whose payload references owner-held objects
        leaves this process, upload those objects so any other node can
        resolve them through the head directory."""
        if not self._deferred_seals and not self._direct_pending:
            return
        todo = []
        with self._direct_cv:
            for h in ids:
                contained = self._deferred_seals.pop(h, None)
                if contained is None:
                    if h in self._direct_pending:
                        # result not here yet: flag so the arrival
                        # handler uploads instead of deferring (the
                        # consumer will dep-wait on the head directory)
                        self._shared_pending.add(h)
                    continue
                entry = self._direct_results.get(h)
                if entry is not None and entry[0] == "val":
                    todo.append((h, entry[1], contained))
        for h, data, contained in todo:
            if not self._upload_owned(h, data, contained):
                # keep the record: the dependent submission will dep-wait,
                # and the next share (or eviction) retries the upload.
                # Also restore the VALUE: a concurrent cap-eviction sweep
                # may have dropped it while the marker was popped (the
                # sweep skips its own upload when it sees no marker) —
                # without this the only copy of the object is lost
                with self._direct_cv:
                    self._deferred_seals.setdefault(h, contained)
                    if h not in self._direct_results:
                        self._direct_results[h] = ("val", data)
                        self._direct_results_order.append(h)

    def _fallback_submit(self, item: dict) -> None:
        """Route a direct-call item through the head-scheduled path (actor
        restarting, worker gone, or no direct route)."""
        from ray_tpu.core.refcount import TRACKER

        with self._direct_cv:
            self._direct_pending.pop(item["ref"], None)
            self._shared_pending.discard(item["ref"])
            unpin = self._direct_arg_pins.pop(item["ref"], ())
            self._direct_cv.notify_all()
        self._submit_actor_lease(
            task_id=item["task_id"],
            actor_id=item["actor_id"],
            name=item["name"],
            payload=item["payload"],
            return_id=item["ref"],
            arg_ids=item["arg_ids"],
        )
        # the lease registers us as the return's holder head-side — the
        # local release is owed from now on (zero-safe: the caller may
        # have dropped the ref already)
        self._flusher.note_registered_live([item["ref"]])
        # the lease (queued before this release can flush) pins the args
        # head-side for the task's lifetime
        for h in unpin:
            TRACKER.decref(h)

    def _direct_note_head_resolved(self, h: str) -> None:
        """A direct-call ref resolved through the head directory while its
        push was still pending: the push was lost (worker-side transient
        RPC failure — the seal reached the head anyway). Drop the pending
        entry and release its arg pins so later gets of this ref go
        straight to the head instead of stalling direct_wait_fallback_s,
        and the entry doesn't leak for the session. Safe: the seal landing
        at the head proves the worker finished with the args."""
        if h not in self._direct_pending:
            return
        from ray_tpu.core.refcount import TRACKER

        with self._direct_cv:
            self._direct_pending.pop(h, None)
            unpin = self._direct_arg_pins.pop(h, ())
            self._direct_cv.notify_all()
        for p in unpin:
            TRACKER.decref(p)

    def _drop_direct_channel(self, actor_id: str, chan) -> None:
        with self._lock:
            if self._direct_channels.get(actor_id) is chan:
                del self._direct_channels[actor_id]

    def _wait_direct(
        self, h: str, deadline: Optional[float]
    ) -> Optional[tuple]:
        """Wait for a direct-call result. Returns the (kind, payload) tuple,
        or None if the ref fell back to the head path (or the push is
        taking long enough that the head directory is the better bet)."""
        # a direct result push can be lost (transient caller-side RPC
        # failure); the seal still reaches the head, so after this long a
        # getter stops trusting the push channel and resolves there
        give_up = time.monotonic() + self._direct_wait_fallback_s
        with self._direct_cv:
            while True:
                if h in self._direct_results:
                    return self._direct_results[h]
                if h not in self._direct_pending:
                    return None
                now = time.monotonic()
                if now >= give_up:
                    return None  # head WaitObject takes over (seal landed)
                wait = min(0.5, give_up - now)
                if deadline is not None:
                    wait = min(wait, deadline - now)
                    if wait <= 0:
                        raise GetTimeoutError(
                            f"get() timed out waiting for {h}"
                        )
                self._direct_cv.wait(timeout=wait)

    def _consume_direct(self, h: str, entry: tuple) -> Tuple[bool, Any]:
        """(resolved, value); raises for error results. Successfully
        consumed entries are dropped — later gets resolve through the head
        directory, which received the same seal."""
        kind, payload = entry
        if kind == "err":
            with self._direct_cv:
                self._direct_results.pop(h, None)
            raise pickle.loads(payload)
        if kind == "val":
            value = self._loads_tracking(payload)
            with self._direct_cv:
                if h not in self._deferred_seals:
                    # owner-held entries stay cached (we are the only
                    # record of the object until share/evict uploads it);
                    # head-sealed entries drop — later gets use the head
                    self._direct_results.pop(h, None)
            return True, value
        # sealed to the actor's node store: fetch from that agent directly
        seal = payload
        with self._lock:
            client = self._agents.get(seal.node_id)
        if client is not None:
            try:
                data = client.call(
                    "FetchObject", {"object_id": h, "purpose": "get"}, timeout=120.0
                )
                value = self._loads_tracking(data)
                with self._direct_cv:
                    self._direct_results.pop(h, None)
                return True, value
            except (RpcError, KeyError, TimeoutError):
                pass
        return False, None  # fall back to the head-located fetch

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------
    def create_actor(
        self,
        cls: type,
        args: tuple,
        kwargs: dict,
        *,
        resources: Dict[str, float],
        name: Optional[str] = None,
        lifetime: Optional[str] = None,
        max_restarts: int = 0,
        max_concurrency: Optional[int] = None,
        concurrency_groups: Optional[Dict[str, int]] = None,
        scheduling_strategy: Any = None,
        runtime_env: Optional[dict] = None,
        **_ignored,
    ) -> RemoteActorHandle:
        from ray_tpu.core.refcount import collect_serialized

        if lifetime not in (None, "detached", "non_detached"):
            raise ValueError(
                f"lifetime must be 'detached' or 'non_detached', "
                f"got {lifetime!r}"
            )

        _ship_module_by_value(cls)
        actor_id = new_id()
        with collect_serialized() as arg_ids:
            payload = wire.dumps((cls, args, kwargs))
        self._flush_deferred_seals(arg_ids)
        lease = LeaseRequest(
            task_id=new_id(),
            name=f"{cls.__name__}.__init__",
            payload=payload,
            return_ids=[],
            resources=resources,
            kind="actor_creation",
            actor_id=actor_id,
            max_retries=0,
            strategy=scheduling_strategy,
            runtime_env=(
                {**(self.runtime_env or {}), **runtime_env}
                if runtime_env
                else self.runtime_env
            ),
            arg_ids=sorted(arg_ids),
            client_id=self.client_id,
        )
        req = {
            "spec": lease,
            "name": name,
            "class_name": cls.__name__,
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "concurrency_groups": dict(concurrency_groups or {}),
            "lifetime": lifetime,
        }
        if name is None:
            # control-plane fast path: unnamed creations ride the ordered
            # client pipeline (one ClientBatch can carry many creations),
            # so a churn loop never serializes on per-creation replies
            # from a loaded head. The actor id is client-minted, so the
            # handle is valid immediately; WaitActor tolerates the
            # message still being in flight.
            self._sender.enqueue("create_actor", req)
        else:
            # named creation stays synchronous: the name-taken error must
            # surface to this caller, not vanish into the pipeline
            self.head.call("CreateActor", req)
        return RemoteActorHandle(self, actor_id, cls)

    def get_actor(self, name: str) -> RemoteActorHandle:
        info = self._read("GetActor", {"name": name})
        return RemoteActorHandle(self, info.actor_id, object)

    def kill_actor(self, handle: RemoteActorHandle, no_restart: bool = True) -> None:
        # rides the same ordered pipeline as creations so a create→kill
        # pair can never arrive reversed; wait=True keeps the
        # "processed by the head when this returns" semantics, and the
        # bounded wait keeps the pre-pipeline contract that a kill
        # against an unreachable head RAISES instead of hanging forever
        self._sender.enqueue(
            "kill_actor",
            {"actor_id": handle._actor_id, "no_restart": no_restart},
            wait=True,
            wait_timeout=30.0,
        )

    def actor_location(self, actor_id: str):
        """(node_id, agent_address) of an actor, or (None, None) while it
        is pending placement. Used for locality-aware dispatch (e.g. the
        serve proxy pinning shm-streaming calls to same-host replicas)."""
        try:
            info = self._read(
                "WaitActor", {"actor_id": actor_id, "timeout": 0.01}
            )
        except Exception:  # noqa: BLE001
            return None, None
        return info.node_id, info.address

    def wait_actor_alive(self, handle: RemoteActorHandle, timeout: float = 30.0):
        """Event-driven: each round is a server-side long-poll (WaitActor),
        so state changes propagate at RPC latency with no sleep loop."""
        deadline = time.monotonic() + timeout
        while True:
            window = min(5.0, max(0.1, deadline - time.monotonic()))
            try:
                info = self._read(
                    "WaitActor",
                    {"actor_id": handle._actor_id, "timeout": window},
                    timeout=window + 15.0,
                )
            except ValueError:
                # creations ride the pipelined client batch: this poll can
                # legitimately beat the creation message to the head (or
                # span a head restart that hasn't replayed it yet) — keep
                # waiting out OUR deadline before declaring it unknown
                if time.monotonic() >= deadline:
                    raise
                continue
            if info.state == "ALIVE":
                return info
            if info.state == "DEAD":
                raise RuntimeError(f"actor {handle._actor_id} died during creation")
            if time.monotonic() >= deadline:
                raise TimeoutError("actor did not become alive in time")

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------
    def put_object(self, value: Any) -> ObjectRef:
        from ray_tpu.core.refcount import collect_serialized

        ref = ObjectRef.new(owner="driver")
        with collect_serialized() as contained:
            data = wire.dumps(value)
        self._flush_deferred_seals(contained)
        self.head.call(
            "PutObject",
            {
                "object_id": ref.hex,
                "data": data,
                "holder": self.client_id,
                "contained_ids": sorted(contained),
            },
        )
        self._flusher.note_registered([ref.hex])
        return ref

    def _loads_tracking(self, data: bytes) -> Any:
        from ray_tpu.core.refcount import loads_tracking

        return loads_tracking(self._flusher, data)

    def object_locations(self, refs: List[ObjectRef]) -> Dict[str, List[str]]:
        """hex -> node ids currently holding the object (best-effort,
        non-blocking; the head's object directory)."""
        try:
            return self._read(
                "LocateObjects", {"object_ids": [r.hex for r in refs]}
            )
        except Exception:  # noqa: BLE001
            return {}

    def object_sizes(self, refs: List[ObjectRef]) -> Dict[str, int]:
        """hex -> sealed byte size (0 = unknown); head object directory."""
        try:
            return self._read(
                "ObjectSizes", {"object_ids": [r.hex for r in refs]}
            )
        except Exception:  # noqa: BLE001
            return {}

    def get_object(self, ref: ObjectRef, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        h = ref.hex
        if self._direct_enabled and (
            h in self._direct_pending or h in self._direct_results
        ):
            entry = self._wait_direct(h, deadline)
            if entry is not None:
                resolved, value = self._consume_direct(h, entry)
                if resolved:
                    return value
        while True:
            # a deferred (owner-held) result can land locally while we're
            # polling a head that will never hear of the object
            if self._direct_enabled:
                with self._direct_cv:
                    entry = self._direct_results.get(h)
                if entry is not None:
                    resolved, value = self._consume_direct(h, entry)
                    if resolved:
                        return value
            poll = 2.0
            budget = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
                poll = min(poll, remaining)
                # head-retry loop bounded by the caller's FULL remaining
                # get() budget (+grace for one in-flight reply) — capping
                # at the poll slice would abort a 60s get() 3s into a 5s
                # head restart
                budget = remaining + 1.0
            try:
                reply = self._read(
                    "WaitObject",
                    {"object_id": ref.hex, "timeout": poll},
                    deadline_s=budget,
                )
            except RpcDeadlineError:
                raise GetTimeoutError(
                    f"get() timed out waiting for {ref} (head unreachable)"
                ) from None
            status = reply["status"]
            if status in ("inline", "error", "located"):
                self._direct_note_head_resolved(h)
            if status == "inline":
                return self._loads_tracking(reply["data"])
            if status == "error":
                raise pickle.loads(reply["error"])
            if status == "located":
                for nid, addr in reply["locations"]:
                    try:
                        data = self._agent(nid, addr).call(
                            "FetchObject",
                            {"object_id": ref.hex, "purpose": "get"},
                            timeout=120.0,
                        )
                        return self._loads_tracking(data)
                    except (RpcError, KeyError, TimeoutError):
                        continue
            if deadline is not None and time.monotonic() >= deadline:
                raise GetTimeoutError(f"get() timed out waiting for {ref}")

    def get_objects(
        self, refs: List[ObjectRef], timeout: Optional[float] = None
    ) -> List[Any]:
        """Batched list-get: one WaitObjectBatch RPC resolves many refs, and
        co-located payloads ride one FetchObjectBatch per node (the
        reference's batched plasma Get, core_worker Get(batch))."""
        deadline = None if timeout is None else time.monotonic() + timeout
        results: Dict[str, tuple] = {}  # hex -> ("val", v) | ("err", exc)
        order = [r.hex for r in refs]
        if self._direct_enabled:
            for h in dict.fromkeys(order):
                if h in self._direct_pending or h in self._direct_results:
                    try:
                        entry = self._wait_direct(h, deadline)
                        if entry is not None:
                            ok, value = self._consume_direct(h, entry)
                            if ok:
                                results[h] = ("val", value)
                    except GetTimeoutError:
                        raise
                    except BaseException as exc:  # noqa: BLE001
                        results[h] = ("err", exc)
        while True:
            unresolved = list(dict.fromkeys(h for h in order if h not in results))
            if not unresolved:
                break
            if self._direct_enabled:
                # late-arriving owner-held results resolve locally; the
                # head may never hear of those objects
                for h in unresolved:
                    entry = self._direct_results.get(h)
                    if entry is not None:
                        try:
                            ok, value = self._consume_direct(h, entry)
                            if ok:
                                results[h] = ("val", value)
                        except BaseException as exc:  # noqa: BLE001
                            results[h] = ("err", exc)
                unresolved = [h for h in unresolved if h not in results]
                if not unresolved:
                    break
            poll = 2.0
            budget = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
                poll = min(poll, remaining)
                budget = remaining + 1.0
            try:
                replies = self._read(
                    "WaitObjectBatch",
                    {"object_ids": unresolved, "timeout": poll},
                    timeout=poll + 30.0,
                    deadline_s=budget,
                )
            except RpcDeadlineError:
                missing = [h for h in order if h not in results]
                raise GetTimeoutError(
                    f"get() timed out waiting for {len(missing)} objects "
                    "(head unreachable)"
                ) from None
            located: Dict[tuple, List[str]] = {}
            for h, rep in zip(unresolved, replies):
                status = rep["status"]
                if status in ("inline", "error", "located"):
                    self._direct_note_head_resolved(h)
                if status == "inline":
                    results[h] = ("val", self._loads_tracking(rep["data"]))
                elif status == "error":
                    results[h] = ("err", pickle.loads(rep["error"]))
                elif status == "located":
                    located.setdefault(tuple(rep["locations"][0]), []).append(h)
            for (nid, addr), hs in located.items():
                try:
                    datas = self._agent(nid, addr).call(
                        "FetchObjectBatch",
                        {"object_ids": hs, "purpose": "get"},
                        timeout=120.0,
                    )
                    for h, d in zip(hs, datas):
                        results[h] = ("val", self._loads_tracking(d))
                except (RpcError, KeyError, TimeoutError):
                    # stale location/partial store: per-ref fallback path
                    for h in hs:
                        try:
                            remaining = None
                            if deadline is not None:
                                remaining = max(0.0, deadline - time.monotonic())
                            results[h] = (
                                "val",
                                self.get_object(ObjectRef(h), remaining),
                            )
                        except BaseException as exc:  # noqa: BLE001
                            results[h] = ("err", exc)
            if deadline is not None and time.monotonic() >= deadline:
                missing = [h for h in order if h not in results]
                if missing:
                    raise GetTimeoutError(
                        f"get() timed out waiting for {len(missing)} objects"
                    )
        out = []
        for h in order:
            kind, v = results[h]
            if kind == "err":
                raise v
            out.append(v)
        return out

    def cancel_object(self, ref: ObjectRef, force: bool = False) -> bool:
        reply = self.head.call(
            "CancelLease", {"object_id": ref.hex, "force": force}
        )
        return bool(reply.get("cancelled"))

    def free_objects(self, refs: List[ObjectRef]) -> None:
        self.head.call("FreeObjects", {"object_ids": [r.hex for r in refs]})

    def _agent(self, node_id: str, address: str) -> RpcClient:
        with self._lock:
            client = self._agents.get(node_id)
            if client is None or client.address != address:
                client = RpcClient(address)
                self._agents[node_id] = client
            return client

    # ------------------------------------------------------------------
    # placement groups
    # ------------------------------------------------------------------
    def create_placement_group(
        self, bundles: List[Dict[str, float]], strategy: str = "PACK"
    ) -> str:
        reply = self.head.call(
            "CreatePlacementGroup", {"bundles": bundles, "strategy": strategy}
        )
        return reply["pg_id"]

    def wait_placement_group(self, pg_id: str, timeout: float = 30.0) -> List[str]:
        deadline = time.monotonic() + timeout
        while True:
            window = min(5.0, max(0.1, deadline - time.monotonic()))
            reply = self._read(
                "WaitPlacementGroup",
                {"pg_id": pg_id, "timeout": window},
                timeout=window + 15.0,
            )
            if reply["ready"]:
                return reply["node_per_bundle"]
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"placement group {pg_id} not ready in {timeout}s"
                )

    def remove_placement_group(self, pg_id: str) -> None:
        self.head.call("RemovePlacementGroup", {"pg_id": pg_id})

    # ------------------------------------------------------------------
    # kv + introspection
    # ------------------------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        self.head.call("KvPut", {"key": key, "value": value})

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._read("KvGet", {"key": key})

    def kv_del(self, key: str) -> None:
        self.head.call("KvDel", {"key": key})

    def kv_keys(self, prefix: str = "") -> List[str]:
        return self._read("KvKeys", {"prefix": prefix})

    def nodes_info(self) -> List[Dict[str, Any]]:
        return self._read("ClusterInfo")["nodes"]

    def pending_resource_demands(self) -> List[Dict[str, float]]:
        """Autoscaler demand feed (queued/infeasible leases + PG bundles)."""
        return self._read("PendingDemands")

    def cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.nodes_info():
            if not n["Alive"]:
                continue
            for k, v in n["Resources"].items():
                out[k] = out.get(k, 0.0) + v
        return out

    def available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.nodes_info():
            if not n["Alive"]:
                continue
            for k, v in n["Available"].items():
                out[k] = out.get(k, 0.0) + v
        return out

    def query_state(self, kind: str = "summary") -> Any:
        return self._read("QueryState", {"kind": kind})

    def timeline(self, filename: Optional[str] = None) -> List[dict]:
        """Chrome-trace of head-observed lease lifecycle events."""
        spans = self._read("Timeline", timeout=60.0)
        if filename:
            import json

            with open(filename, "w") as f:
                json.dump(spans, f)
        return spans

    def shutdown(self) -> None:
        from ray_tpu.core import refcount

        for chan in list(self._direct_channels.values()):
            chan.stop()
        self._direct_channels.clear()
        if self._callback_server is not None:
            self._callback_server.stop()
            self._callback_server = None
        if self._owns_flusher:
            # release every id this driver still counts so the cluster can
            # free driver-owned objects (job-exit cleanup analog)
            self._flusher.stop(release_all=True)
            refcount.clear_consumer(self._flusher)
        self._sender.stop()
        try:
            # clean driver exit: the head reaps this client's non-detached
            # actors (detached ones survive — reference job-exit
            # semantics). Best-effort: a crashed driver skips this and
            # its actors linger until killed explicitly.
            self.head.call(
                "DisconnectClient", {"client_id": self.client_id}, timeout=5.0
            )
        except Exception:  # noqa: BLE001 - best-effort: call() re-raises
            pass  # server-side exceptions verbatim (not just RpcError)
        self._pipe_chan.close()
        self.head.close()
        with self._lock:
            for client in self._agents.values():
                client.close()
            self._agents.clear()


def connect(address: str, runtime_env: Optional[dict] = None) -> RemoteRuntime:
    return RemoteRuntime(address, runtime_env=runtime_env)
