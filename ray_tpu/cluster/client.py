"""Driver-side client runtime for a distributed ray_tpu cluster.

``ray_tpu.init(address="host:port")`` swaps the in-process Runtime for a
``RemoteRuntime`` — the same duck-typed surface the public API calls
(submit / put_object / get_object / wait / actors / PGs), but every
operation is an RPC to the head or a node agent. This is the moral
equivalent of the reference driver's CoreWorker connecting to the GCS
and raylets (/root/reference/python/ray/_private/worker.py:1406), and it
doubles as the Ray-Client analog (util/client/) since a driver can be
anywhere with connectivity to the cluster.
"""
from __future__ import annotations

import inspect
import pickle
import sys
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu.core.object_store import GetTimeoutError, ObjectRef
from ray_tpu.core.runtime import TaskSpec

from .common import INLINE_OBJECT_MAX, LeaseRequest, new_id
from .rpc import RpcClient, RpcError

_BY_VALUE_REGISTERED: set = set()


def _ship_module_by_value(obj: Any) -> None:
    """User code living outside site-packages (driver scripts, test files)
    isn't importable on workers — pickle its module by value (the reference
    ships the function definition in the task spec the same way)."""
    try:
        mod = inspect.getmodule(obj)
        if mod is None:
            return
        name = getattr(mod, "__name__", "")
        if name in _BY_VALUE_REGISTERED or name == "__main__":
            if name == "__main__":
                return  # cloudpickle already serializes __main__ by value
            return
        f = getattr(mod, "__file__", None)
        if not f:
            return
        if (
            "site-packages" in f
            or "/ray_tpu/" in f
            or f.startswith(sys.prefix)
            or f.startswith(getattr(sys, "base_prefix", sys.prefix))
        ):
            return
        cloudpickle.register_pickle_by_value(mod)
        _BY_VALUE_REGISTERED.add(name)
    except Exception:  # noqa: BLE001 - best-effort
        pass


class _RemoteStore:
    """ray.wait support against the head's object directory."""

    def __init__(self, runtime: "RemoteRuntime"):
        self._rt = runtime

    def wait_many(
        self,
        refs: List[ObjectRef],
        num_returns: int,
        timeout: Optional[float],
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectRef] = []
        pending = list(refs)
        while len(ready) < num_returns:
            still: List[ObjectRef] = []
            for r in pending:
                if len(ready) >= num_returns:
                    still.append(r)
                    continue
                remaining = 0.05
                if deadline is not None:
                    remaining = min(remaining, max(0.0, deadline - time.monotonic()))
                reply = self._rt.head.call(
                    "WaitObject",
                    {"object_id": r.hex, "timeout": remaining},
                    timeout=15.0,
                )
                if reply["status"] != "pending":
                    ready.append(r)
                else:
                    still.append(r)
            pending = still
            if deadline is not None and time.monotonic() >= deadline:
                break
        return ready, pending


class RemotePlacementGroup:
    """Driver-side PG handle for cluster mode (util/placement_group.py
    analog); picklable — it carries only ids/specs."""

    def __init__(self, pg_id: str, bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    def wait(self, timeout_seconds: float = 30) -> bool:
        from ray_tpu.core.runtime import get_runtime

        try:
            get_runtime().wait_placement_group(self.id, timeout=timeout_seconds)
            return True
        except TimeoutError:
            return False

    def __repr__(self) -> str:
        return f"RemotePlacementGroup({self.id[:8]}, {self.strategy})"


class RemoteActorHandle:
    def __init__(self, runtime: "RemoteRuntime", actor_id: str, cls: type):
        self._runtime = runtime
        self._actor_id = actor_id
        self._cls = cls

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _RemoteMethod(self._runtime, self._actor_id, name)

    def __reduce__(self):
        return (_rebuild_actor_handle, (self._actor_id, self._cls))


def _rebuild_actor_handle(actor_id: str, cls: type):
    from ray_tpu.core.runtime import get_runtime

    return RemoteActorHandle(get_runtime(), actor_id, cls)


class _RemoteMethod:
    def __init__(self, runtime: "RemoteRuntime", actor_id: str, method: str):
        self._runtime = runtime
        self._actor_id = actor_id
        self._method = method

    def remote(self, *args, **kwargs) -> ObjectRef:
        return self._runtime.submit_actor_method(
            self._actor_id, self._method, args, kwargs
        )


class _PipelinedSender:
    """Client→head submission pipeline (the reference's task-submission
    pipelining, core_worker/task_submission/normal_task_submitter.h): lease
    submissions and refcount updates ride ONE ordered queue, coalesced into
    ``ClientBatch`` RPCs. An idle sender ships immediately (no added
    latency); under load everything queued while the previous RPC was in
    flight merges into one message. Ordering between a submission that
    registers return-id holders and a later release of those ids is
    preserved by construction."""

    MAX_BATCH = 512

    def __init__(self, client: RpcClient):
        self._client = client
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._enqueued = 0
        self._acked = 0
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name="lease-pipeline", daemon=True
        )
        self._thread.start()

    def enqueue(self, kind: str, payload: Any, wait: bool = False) -> None:
        with self._cv:
            if self._stop:
                return
            self._q.append((kind, payload))
            self._enqueued += 1
            ticket = self._enqueued
            self._cv.notify_all()
        if wait:
            with self._cv:
                while self._acked < ticket and not self._stop:
                    self._cv.wait(timeout=0.5)

    def _loop(self) -> None:
        import logging

        log = logging.getLogger("ray_tpu.cluster.client")
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(timeout=0.5)
                if not self._q:
                    if self._stop:
                        return
                    continue
                n = min(len(self._q), self.MAX_BATCH)
                batch = [self._q.popleft() for _ in range(n)]
            delivered = False
            attempts = 0
            while not delivered:
                try:
                    attempts += 1
                    if attempts > 1:
                        log.warning(
                            "ClientBatch re-send #%d (%d items)",
                            attempts,
                            len(batch),
                        )
                    self._client.call(
                        "ClientBatch",
                        batch,
                        timeout=60.0,
                        retries=8,
                        retry_interval=0.25,
                    )
                    delivered = True
                except RpcError:
                    # a dropped lease would strand its caller's get()
                    # forever and a dropped release leaks the object —
                    # keep the batch and retry until the head comes back
                    # (or this runtime shuts down)
                    with self._cv:
                        if self._stop:
                            return
                    log.warning(
                        "head unreachable; retrying %d control items",
                        len(batch),
                    )
                    time.sleep(0.5)
            with self._cv:
                self._acked += len(batch)
                self._cv.notify_all()

    def drain(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        with self._cv:
            target = self._enqueued
            while self._acked < target and time.monotonic() < deadline:
                self._cv.wait(timeout=0.2)

    def stop(self) -> None:
        self.drain()
        with self._cv:
            self._stop = True
            self._cv.notify_all()


class RemoteRuntime:
    """Duck-typed Runtime whose backend is a live cluster."""

    is_remote = True

    def __init__(self, address: str, runtime_env: Optional[dict] = None):
        self.address = address
        self.head = RpcClient(address)
        self.head.call("Ping", timeout=10.0, retries=20, retry_interval=0.25)
        self.runtime_env = runtime_env
        self._agents: Dict[str, RpcClient] = {}
        self._lock = threading.Lock()
        self.store = _RemoteStore(self)
        self.metrics: Dict[str, int] = {}
        # distributed refcounting: this process's holder identity + release
        # reporter. Inside a cluster worker the worker's flusher (which
        # routes via its agent) is already installed and is reused.
        from ray_tpu.core import refcount

        self.client_id = refcount.get_holder_id()
        # dedicated channel for the pipeline: its traffic during a head
        # outage must not push the main channel into gRPC reconnect backoff
        self._pipe_chan = RpcClient(address)
        self._sender = _PipelinedSender(self._pipe_chan)
        incumbent = refcount.current_consumer()
        if isinstance(incumbent, refcount.RefFlusher):
            self._flusher = incumbent
            self._owns_flusher = False
        else:
            self._flusher = refcount.RefFlusher(
                lambda inc, dec: self._sender.enqueue(
                    "ref",
                    {"holder": self.client_id, "increfs": inc, "decrefs": dec},
                    wait=True,
                ),
                holder=self.client_id,
            )
            refcount.install_consumer(self._flusher)
            self._owns_flusher = True

    def _read(self, method: str, payload: Any = None, timeout: float = 30.0):
        """Idempotent head reads retry through transport blips — a client
        rides through a head restart the way the reference's GCS client
        does (gcs_rpc_client.h retry budgets)."""
        return self.head.call(
            method, payload, timeout=timeout, retries=8, retry_interval=0.25
        )

    # ------------------------------------------------------------------
    # tasks
    # ------------------------------------------------------------------
    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        from ray_tpu.core.refcount import collect_serialized

        _ship_module_by_value(spec.func)
        with collect_serialized() as arg_ids:
            payload = cloudpickle.dumps((spec.func, spec.args, spec.kwargs))
        deps = [a.hex for a in spec.args if isinstance(a, ObjectRef)]
        deps += [
            v.hex for v in spec.kwargs.values() if isinstance(v, ObjectRef)
        ]
        lease = LeaseRequest(
            task_id=spec.task_id,
            name=spec.name,
            payload=payload,
            return_ids=[r.hex for r in spec.returns],
            resources=spec.resources,
            kind="task",
            max_retries=spec.max_retries,
            retry_exceptions=spec.retry_exceptions,
            strategy=spec.strategy,
            runtime_env=self.runtime_env,
            arg_ids=sorted(arg_ids),
            deps=deps,
            client_id=self.client_id,
        )
        self._sender.enqueue("lease", lease)
        self._flusher.note_registered(lease.return_ids)
        return spec.returns

    def submit_actor_method(
        self, actor_id: str, method: str, args: tuple, kwargs: dict
    ) -> ObjectRef:
        from ray_tpu.core.refcount import collect_serialized

        ref = ObjectRef.new(owner=actor_id)
        with collect_serialized() as arg_ids:
            payload = cloudpickle.dumps((method, args, kwargs))
        lease = LeaseRequest(
            task_id=new_id(),
            name=f"{actor_id[:8]}.{method}",
            payload=payload,
            return_ids=[ref.hex],
            resources={},
            kind="actor_method",
            actor_id=actor_id,
            max_retries=0,
            arg_ids=sorted(arg_ids),
            client_id=self.client_id,
        )
        self._sender.enqueue("lease", lease)
        self._flusher.note_registered(lease.return_ids)
        return ref

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------
    def create_actor(
        self,
        cls: type,
        args: tuple,
        kwargs: dict,
        *,
        resources: Dict[str, float],
        name: Optional[str] = None,
        max_restarts: int = 0,
        max_concurrency: Optional[int] = None,
        concurrency_groups: Optional[Dict[str, int]] = None,
        scheduling_strategy: Any = None,
        **_ignored,
    ) -> RemoteActorHandle:
        from ray_tpu.core.refcount import collect_serialized

        _ship_module_by_value(cls)
        actor_id = new_id()
        with collect_serialized() as arg_ids:
            payload = cloudpickle.dumps((cls, args, kwargs))
        lease = LeaseRequest(
            task_id=new_id(),
            name=f"{cls.__name__}.__init__",
            payload=payload,
            return_ids=[],
            resources=resources,
            kind="actor_creation",
            actor_id=actor_id,
            max_retries=0,
            strategy=scheduling_strategy,
            runtime_env=self.runtime_env,
            arg_ids=sorted(arg_ids),
            client_id=self.client_id,
        )
        self.head.call(
            "CreateActor",
            {
                "spec": lease,
                "name": name,
                "class_name": cls.__name__,
                "max_restarts": max_restarts,
                "max_concurrency": max_concurrency,
                "concurrency_groups": dict(concurrency_groups or {}),
            },
        )
        return RemoteActorHandle(self, actor_id, cls)

    def get_actor(self, name: str) -> RemoteActorHandle:
        info = self._read("GetActor", {"name": name})
        return RemoteActorHandle(self, info.actor_id, object)

    def kill_actor(self, handle: RemoteActorHandle, no_restart: bool = True) -> None:
        self.head.call(
            "KillActor", {"actor_id": handle._actor_id, "no_restart": no_restart}
        )

    def wait_actor_alive(self, handle: RemoteActorHandle, timeout: float = 30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = self._read("GetActor", {"actor_id": handle._actor_id})
            if info.state == "ALIVE":
                return info
            if info.state == "DEAD":
                raise RuntimeError(f"actor {handle._actor_id} died during creation")
            time.sleep(0.05)
        raise TimeoutError("actor did not become alive in time")

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------
    def put_object(self, value: Any) -> ObjectRef:
        from ray_tpu.core.refcount import collect_serialized

        ref = ObjectRef.new(owner="driver")
        with collect_serialized() as contained:
            data = cloudpickle.dumps(value)
        self.head.call(
            "PutObject",
            {
                "object_id": ref.hex,
                "data": data,
                "holder": self.client_id,
                "contained_ids": sorted(contained),
            },
        )
        self._flusher.note_registered([ref.hex])
        return ref

    def _loads_tracking(self, data: bytes) -> Any:
        from ray_tpu.core.refcount import loads_tracking

        return loads_tracking(self._flusher, data)

    def get_object(self, ref: ObjectRef, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            poll = 2.0
            if deadline is not None:
                poll = min(poll, max(0.0, deadline - time.monotonic()))
            reply = self._read(
                "WaitObject", {"object_id": ref.hex, "timeout": poll}
            )
            status = reply["status"]
            if status == "inline":
                return self._loads_tracking(reply["data"])
            if status == "error":
                raise pickle.loads(reply["error"])
            if status == "located":
                for nid, addr in reply["locations"]:
                    try:
                        data = self._agent(nid, addr).call(
                            "FetchObject", {"object_id": ref.hex}, timeout=120.0
                        )
                        return self._loads_tracking(data)
                    except (RpcError, KeyError):
                        continue
            if deadline is not None and time.monotonic() >= deadline:
                raise GetTimeoutError(f"get() timed out waiting for {ref}")

    def get_objects(
        self, refs: List[ObjectRef], timeout: Optional[float] = None
    ) -> List[Any]:
        """Batched list-get: one WaitObjectBatch RPC resolves many refs, and
        co-located payloads ride one FetchObjectBatch per node (the
        reference's batched plasma Get, core_worker Get(batch))."""
        deadline = None if timeout is None else time.monotonic() + timeout
        results: Dict[str, tuple] = {}  # hex -> ("val", v) | ("err", exc)
        order = [r.hex for r in refs]
        while True:
            unresolved = list(dict.fromkeys(h for h in order if h not in results))
            if not unresolved:
                break
            poll = 2.0
            if deadline is not None:
                poll = min(poll, max(0.0, deadline - time.monotonic()))
            replies = self._read(
                "WaitObjectBatch",
                {"object_ids": unresolved, "timeout": poll},
                timeout=poll + 30.0,
            )
            located: Dict[tuple, List[str]] = {}
            for h, rep in zip(unresolved, replies):
                status = rep["status"]
                if status == "inline":
                    results[h] = ("val", self._loads_tracking(rep["data"]))
                elif status == "error":
                    results[h] = ("err", pickle.loads(rep["error"]))
                elif status == "located":
                    located.setdefault(tuple(rep["locations"][0]), []).append(h)
            for (nid, addr), hs in located.items():
                try:
                    datas = self._agent(nid, addr).call(
                        "FetchObjectBatch", {"object_ids": hs}, timeout=120.0
                    )
                    for h, d in zip(hs, datas):
                        results[h] = ("val", self._loads_tracking(d))
                except (RpcError, KeyError):
                    # stale location/partial store: per-ref fallback path
                    for h in hs:
                        try:
                            remaining = None
                            if deadline is not None:
                                remaining = max(0.0, deadline - time.monotonic())
                            results[h] = (
                                "val",
                                self.get_object(ObjectRef(h), remaining),
                            )
                        except BaseException as exc:  # noqa: BLE001
                            results[h] = ("err", exc)
            if deadline is not None and time.monotonic() >= deadline:
                missing = [h for h in order if h not in results]
                if missing:
                    raise GetTimeoutError(
                        f"get() timed out waiting for {len(missing)} objects"
                    )
        out = []
        for h in order:
            kind, v = results[h]
            if kind == "err":
                raise v
            out.append(v)
        return out

    def free_objects(self, refs: List[ObjectRef]) -> None:
        self.head.call("FreeObjects", {"object_ids": [r.hex for r in refs]})

    def _agent(self, node_id: str, address: str) -> RpcClient:
        with self._lock:
            client = self._agents.get(node_id)
            if client is None or client.address != address:
                client = RpcClient(address)
                self._agents[node_id] = client
            return client

    # ------------------------------------------------------------------
    # placement groups
    # ------------------------------------------------------------------
    def create_placement_group(
        self, bundles: List[Dict[str, float]], strategy: str = "PACK"
    ) -> str:
        reply = self.head.call(
            "CreatePlacementGroup", {"bundles": bundles, "strategy": strategy}
        )
        return reply["pg_id"]

    def wait_placement_group(self, pg_id: str, timeout: float = 30.0) -> List[str]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            reply = self._read(
                "WaitPlacementGroup", {"pg_id": pg_id, "timeout": 2.0}
            )
            if reply["ready"]:
                return reply["node_per_bundle"]
            time.sleep(0.05)
        raise TimeoutError(f"placement group {pg_id} not ready in {timeout}s")

    def remove_placement_group(self, pg_id: str) -> None:
        self.head.call("RemovePlacementGroup", {"pg_id": pg_id})

    # ------------------------------------------------------------------
    # kv + introspection
    # ------------------------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        self.head.call("KvPut", {"key": key, "value": value})

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._read("KvGet", {"key": key})

    def kv_del(self, key: str) -> None:
        self.head.call("KvDel", {"key": key})

    def kv_keys(self, prefix: str = "") -> List[str]:
        return self._read("KvKeys", {"prefix": prefix})

    def nodes_info(self) -> List[Dict[str, Any]]:
        return self._read("ClusterInfo")["nodes"]

    def cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.nodes_info():
            if not n["Alive"]:
                continue
            for k, v in n["Resources"].items():
                out[k] = out.get(k, 0.0) + v
        return out

    def available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.nodes_info():
            if not n["Alive"]:
                continue
            for k, v in n["Available"].items():
                out[k] = out.get(k, 0.0) + v
        return out

    def query_state(self, kind: str = "summary") -> Any:
        return self._read("QueryState", {"kind": kind})

    def timeline(self, filename: Optional[str] = None) -> List[dict]:
        """Chrome-trace of head-observed lease lifecycle events."""
        spans = self._read("Timeline", timeout=60.0)
        if filename:
            import json

            with open(filename, "w") as f:
                json.dump(spans, f)
        return spans

    def shutdown(self) -> None:
        from ray_tpu.core import refcount

        if self._owns_flusher:
            # release every id this driver still counts so the cluster can
            # free driver-owned objects (job-exit cleanup analog)
            self._flusher.stop(release_all=True)
            refcount.clear_consumer(self._flusher)
        self._sender.stop()
        self._pipe_chan.close()
        self.head.close()
        with self._lock:
            for client in self._agents.values():
                client.close()
            self._agents.clear()


def connect(address: str, runtime_env: Optional[dict] = None) -> RemoteRuntime:
    return RemoteRuntime(address, runtime_env=runtime_env)
