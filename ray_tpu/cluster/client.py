"""Driver-side client runtime for a distributed ray_tpu cluster.

``ray_tpu.init(address="host:port")`` swaps the in-process Runtime for a
``RemoteRuntime`` — the same duck-typed surface the public API calls
(submit / put_object / get_object / wait / actors / PGs), but every
operation is an RPC to the head or a node agent. This is the moral
equivalent of the reference driver's CoreWorker connecting to the GCS
and raylets (/root/reference/python/ray/_private/worker.py:1406), and it
doubles as the Ray-Client analog (util/client/) since a driver can be
anywhere with connectivity to the cluster.
"""
from __future__ import annotations

import hashlib
import inspect
import os
import pickle
import sys
import threading
import time
import logging
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

logger = logging.getLogger("ray_tpu.cluster.client")

from ray_tpu.core.object_store import GetTimeoutError, ObjectRef
from ray_tpu.core.runtime import TaskSpec

from . import serialization as wire
from .common import INLINE_OBJECT_MAX, LeaseRequest, new_id
from .rpc import (
    RpcClient,
    RpcDeadlineError,
    RpcError,
    RpcNotLeaderError,
    RpcServer,
    RpcStaleEpochError,
)

from ray_tpu.util.metrics import Counter as _Counter

# lease-cached direct dispatch (owner-side): cache effectiveness + the
# spillbacks that route leased work back through head scheduling
LEASE_CACHE_HITS = _Counter(
    "task_lease_cache_hits_total",
    "Task submissions streamed straight to a cached worker lease.",
)
LEASE_CACHE_MISSES = _Counter(
    "task_lease_cache_misses_total",
    "Task submissions that took the per-task head path (no usable lease).",
)
LEASE_SPILLBACKS = _Counter(
    "task_lease_spillbacks_total",
    "Leased tasks re-routed to head scheduling (lease loss, stall "
    "recall, or worker rejection).",
)

from .common import DISPATCH_OVERHEAD_US, dispatch_sampled as _sampled

_BY_VALUE_REGISTERED: set = set()

# precomputed frame for argless submissions (see RemoteRuntime.submit)
_EMPTY_ARGS_PAYLOAD: bytes = wire.dumps(((), {}))


def _ship_module_by_value(obj: Any) -> None:
    """User code living outside site-packages (driver scripts, test files)
    isn't importable on workers — pickle its module by value (the reference
    ships the function definition in the task spec the same way)."""
    try:
        mod = inspect.getmodule(obj)
        if mod is None:
            return
        name = getattr(mod, "__name__", "")
        if name in _BY_VALUE_REGISTERED or name == "__main__":
            if name == "__main__":
                return  # cloudpickle already serializes __main__ by value
            return
        f = getattr(mod, "__file__", None)
        if not f:
            return
        if (
            "site-packages" in f
            or "/ray_tpu/" in f
            or f.startswith(sys.prefix)
            or f.startswith(getattr(sys, "base_prefix", sys.prefix))
        ):
            return
        cloudpickle.register_pickle_by_value(mod)
        _BY_VALUE_REGISTERED.add(name)
    except Exception:  # noqa: BLE001 - best-effort
        pass


class _RemoteStore:
    """ray.wait support against the head's object directory."""

    def __init__(self, runtime: "RemoteRuntime"):
        self._rt = runtime

    def wait_many(
        self,
        refs: List[ObjectRef],
        num_returns: int,
        timeout: Optional[float],
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        """One multiplexed server-side long-poll per window: the head
        blocks until num_returns ids resolve (WaitObjectBatch num_returns),
        so readiness propagates at RPC latency without client sleep
        loops."""
        from ray_tpu.config import cfg

        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectRef] = []
        pending = list(refs)
        t_start = time.monotonic()
        while pending and len(ready) < num_returns:
            # direct-call results resolve locally without a head round trip
            if self._rt._push_enabled:
                still: List[ObjectRef] = []
                for r in pending:
                    if (
                        len(ready) < num_returns
                        and r.hex in self._rt._direct_results
                    ):
                        ready.append(r)
                    else:
                        still.append(r)
                pending = still
                if not pending or len(ready) >= num_returns:
                    break
                # every remaining ref is a direct call whose result will
                # arrive by push: park on the push channel's condition
                # variable instead of a head long-poll — a WaitObjectBatch
                # RPC would sit blind for its whole window while pushes
                # land locally. After the fallback grace (push may have
                # been lost) the head path takes over.
                if (
                    all(h.hex in self._rt._direct_pending for h in pending)
                    and time.monotonic() - t_start
                    < self._rt._direct_wait_fallback_s
                ):
                    wait_s = 0.2
                    if deadline is not None:
                        wait_s = min(
                            wait_s, max(0.0, deadline - time.monotonic())
                        )
                    with self._rt._direct_cv:
                        if not any(
                            h.hex in self._rt._direct_results
                            for h in pending
                        ):
                            self._rt._direct_cv.wait(timeout=wait_s)
                    if (
                        deadline is not None
                        and time.monotonic() >= deadline
                    ):
                        break
                    continue
            window = 5.0
            if deadline is not None:
                window = min(window, max(0.0, deadline - time.monotonic()))
            replies = self._rt.head.call(
                "WaitObjectBatch",
                {
                    "object_ids": [r.hex for r in pending],
                    "timeout": window,
                    "num_returns": max(1, num_returns - len(ready)),
                },
                timeout=window + 15.0,
            )
            still = []
            for r, rep in zip(pending, replies):
                if len(ready) < num_returns and rep["status"] != "pending":
                    ready.append(r)
                else:
                    still.append(r)
            pending = still
            if deadline is not None and time.monotonic() >= deadline:
                break
        return ready, pending


class RemotePlacementGroup:
    """Driver-side PG handle for cluster mode (util/placement_group.py
    analog); picklable — it carries only ids/specs."""

    def __init__(self, pg_id: str, bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    def wait(self, timeout_seconds: float = 30) -> bool:
        from ray_tpu.core.runtime import get_runtime

        try:
            get_runtime().wait_placement_group(self.id, timeout=timeout_seconds)
            return True
        except TimeoutError:
            return False

    def __repr__(self) -> str:
        return f"RemotePlacementGroup({self.id[:8]}, {self.strategy})"


class _DirectActorChannel:
    """Caller-side direct submission channel to one actor's worker process
    (reference: ActorTaskSubmitter's per-actor ordered send queue,
    core_worker/task_submission/actor_task_submitter.h:79). Methods are
    coalesced into DirectPushBatch RPCs straight to the worker; results
    come back via the runtime's callback server. The head never sees the
    hot path — it only receives coalesced seal reports for the object
    directory. On any transport failure the channel drains its queue back
    through the head-scheduled lease path (which owns restart semantics);
    a batch that died mid-flight may re-execute (at-least-once, like the
    reference's actor task retries).

    Scheduling: this channel is a SOURCE on the runtime's fused event
    loop — ``step`` forms whole windows and offloads the blocking RPC to
    the shared sender pool (at most one action in flight per channel, so
    per-actor ordering is preserved); there is no per-channel thread.
    Worker resolution (a rare multi-RPC dance that can legitimately
    block for a minute on a pending actor) runs on its own short-lived
    thread so it can never starve the sender pool."""

    MAX_BATCH = 256

    def __init__(self, runtime: "RemoteRuntime", actor_id: str):
        self._rt = runtime
        self.actor_id = actor_id
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._dead = False
        self._busy = False  # an action is in flight on the sender pool
        self._accepted: Dict[str, dict] = {}  # ref hex -> item (unresolved)
        self._worker: Optional[RpcClient] = None
        self._last_progress = time.monotonic()
        self._loop = runtime._hotloop
        if not self._loop.register(self):
            # loop stopped (shutdown race): this channel can never be
            # scheduled — born dead, every submit falls back to the head
            self._dead = True
            return
        threading.Thread(
            target=self._run_resolve,
            name=f"direct-resolve-{actor_id[:6]}",
            daemon=True,
        ).start()

    def submit(self, item: dict) -> None:
        with self._cv:
            if not self._dead:
                self._q.append(item)
                accepted = True
            else:
                accepted = False
        if accepted:
            self._loop.wake(self)
            return
        # fallback OUTSIDE self._cv: _fallback_submit takes the runtime's
        # _direct_cv, and result delivery holds _direct_cv while calling
        # on_result — nesting here would be an AB-BA deadlock
        self._rt._fallback_submit(item)

    def submit_many(self, items: List[dict]) -> None:
        """Window submission: one lock pass + ONE loop wake for a whole
        batch of calls (the Data executor dispatches per-actor block
        windows through here — per-item notify overhead was a measurable
        slice of the 50k-block submit path)."""
        with self._cv:
            if not self._dead:
                self._q.extend(items)
                accepted = True
            else:
                accepted = False
        if accepted:
            self._loop.wake(self)
            return
        for item in items:
            self._rt._fallback_submit(item)

    def on_result(self, ref_hex: str) -> None:
        # single GIL-atomic pop; deliberately lock-free (callers hold the
        # runtime's _direct_cv — see submit() ordering note)
        self._accepted.pop(ref_hex, None)
        self._last_progress = time.monotonic()

    def _resolve_worker(self) -> Optional[RpcClient]:
        handle = RemoteActorHandle(self._rt, self.actor_id, object)
        info = self._rt.wait_actor_alive(handle, timeout=60.0)
        agent = self._rt._agent(info.node_id, info.address)
        reply = agent.call(
            "ActorWorkerAddress", {"actor_id": self.actor_id}, timeout=10.0
        )
        return RpcClient(reply["address"])

    def _run_resolve(self) -> None:
        try:
            worker = self._resolve_worker()
        except BaseException as exc:  # noqa: BLE001
            logger.info(
                "direct channel to %s unavailable (%r); using head path",
                self.actor_id[:8],
                exc,
            )
            self._fail_over()
            return
        with self._cv:
            self._worker = worker
        self._loop.wake(self)

    def step(self, now: float) -> Optional[float]:
        """Fused-loop callback: drain the queue into one window, or probe
        a silent worker that owes results. Non-blocking by contract."""
        batch: List[dict] = []
        action = None
        with self._cv:
            if self._dead:
                return None
            if self._busy or self._worker is None:
                return None  # completion/resolve wakes us
            if self._q:
                while self._q and len(batch) < self.MAX_BATCH:
                    batch.append(self._q.popleft())
                for it in batch:
                    self._accepted[it["ref"]] = it
                action = "send"
                self._busy = True
            elif self._accepted and now - self._last_progress >= 2.0:
                # watchdog: accepted-but-unresolved items + silent worker
                # means the worker may have died mid-call
                action = "probe"
                self._busy = True
            owed = bool(self._accepted)
        if action == "send":
            self._loop.note_batch(len(batch))
            if not self._loop.offload(self, self._run_send, batch):
                # pool gone (shutdown): hand the window back to the
                # queue front so nothing is stranded as accepted-but-
                # never-sent
                with self._cv:
                    for it in reversed(batch):
                        self._accepted.pop(it["ref"], None)
                        self._q.appendleft(it)
                    self._busy = False
            return None
        if action == "probe":
            if not self._loop.offload(self, self._run_probe):
                with self._cv:
                    self._busy = False
            return None
        return now + 2.0 if owed else None

    def _run_send(self, batch: List[dict]) -> None:
        try:
            # strip client-local fields (e.g. the live arg refs kept
            # to pin args until completion) from the wire items
            wire = [
                {k: v for k, v in it.items() if not k.startswith("_")}
                for it in batch
            ]
            accepts = self._worker.call(
                "DirectPushBatch",
                {
                    "client_addr": self._rt._callback_address(),
                    "items": wire,
                },
                timeout=60.0,
            )
            done = []
            for it, status in zip(batch, accepts):
                if isinstance(status, dict):
                    # fast path: the result rode the accept reply
                    done.append(status["done"])
                elif status != "accepted":
                    with self._cv:
                        self._accepted.pop(it["ref"], None)
                    self._rt._fallback_submit(it)
            if done:
                self._rt._process_direct_results(done)
        except RpcError:
            self._fail_over(batch)
            return
        finally:
            with self._cv:
                self._busy = False

    def _run_probe(self) -> None:
        try:
            self._worker.call("Ping", timeout=5.0)
            self._last_progress = time.monotonic()
        except RpcError:
            self._fail_over()
        finally:
            with self._cv:
                self._busy = False

    def _fail_over(self, batch: Optional[list] = None) -> None:
        """Worker unreachable: everything unresolved re-routes through the
        head, which knows whether the actor is restarting or dead."""
        with self._cv:
            self._dead = True
            items = list(self._accepted.values())
            self._accepted.clear()
            queued = list(self._q)
            self._q.clear()
        self._loop.unregister(self)
        seen = set()
        for it in (batch or []) + items + queued:
            if it["ref"] not in seen:
                seen.add(it["ref"])
                self._rt._fallback_submit(it)
        self._rt._drop_direct_channel(self.actor_id, self)

    def stop(self) -> None:
        with self._cv:
            self._dead = True
        self._loop.unregister(self)


class _TaskLeaseChannel:
    """One cached worker lease + its direct submission pipe (task
    leases). The head granted this owner a pinned worker for one task
    shape; same-shape tasks stream here in ``LeaseTaskBatch`` windows
    with no head hop — the reference raylet's worker lease
    (local_lease_manager.h), held long enough to amortize placement
    across a whole stream. Execution is strictly sequential worker-side
    (the lease holds ONE task's resources); ``max_inflight`` is pipeline
    depth. Results arrive on the runtime's direct-results callback
    exactly like direct actor calls.

    Liveness by construction: a head-of-line task that blocks (e.g. a
    rendezvous peer waiting on its siblings) stops the flow of results;
    after ``task_lease_stall_s`` the channel RECALLS the worker's queued
    items and spills them — plus its local queue — back to head
    scheduling, so followers run elsewhere instead of deadlocking behind
    it. Any transport failure spills everything unresolved the same way
    (chaos-safe: worker death, node death, breaker-open all land here)."""

    MAX_BATCH = 128

    def __init__(
        self,
        runtime: "RemoteRuntime",
        manager: "_TaskLeaseManager",
        shape_key: tuple,
        grant: dict,
    ):
        self._rt = runtime
        self._mgr = manager
        self.shape_key = shape_key
        self.lease_id = grant["lease_id"]
        self.key = f"lease:{self.lease_id}"  # _direct_channels registry key
        self.node_id = grant.get("node_id")
        self.max_inflight = max(1, int(grant.get("max_inflight") or 32))
        self.ttl = max(0.5, float(grant.get("ttl_s") or 5.0))
        self.accel_env = grant.get("accel_env")
        self._stall_s = manager.stall_s
        self._worker = RpcClient(grant["worker_address"])
        self._q: deque = deque()
        self._inflight: Dict[str, dict] = {}  # ref hex -> item
        self._cv = threading.Condition()
        self.dead = False
        self._stalled = False
        self._busy = False  # an action is in flight on the sender pool
        now = time.monotonic()
        self._last_activity = now
        self._last_send = now
        self._last_result = now
        self._last_probe = now
        self._last_renew = now
        with runtime._lock:
            runtime._direct_channels[self.key] = self
        self._loop = runtime._hotloop
        if not self._loop.register(self):
            # loop stopped (shutdown race): born dead — submits spill to
            # head scheduling, the manager prunes dead channels
            self.dead = True

    # lock-free reads (GIL-atomic lens): the manager's pick runs per
    # submission and must not serialize on the channel lock
    def depth(self) -> int:
        return len(self._q) + len(self._inflight)

    def accepting(self) -> bool:
        return not self.dead and not self._stalled

    def submit(self, item: dict) -> None:
        with self._cv:
            if not self.dead:
                self._q.append(item)
                self._last_activity = time.monotonic()
                accepted = True
            else:
                accepted = False
        if accepted:
            self._loop.wake(self)
            return
        # spill OUTSIDE self._cv (lock order: runtime._direct_cv may be
        # taken inside _lease_spill; result delivery holds _direct_cv
        # while calling on_result, which takes self._cv)
        self._rt._lease_spill(item)

    def on_result(self, ref_hex: str) -> None:
        # called under the runtime's _direct_cv; self._cv nests inside it
        # everywhere (never the reverse)
        with self._cv:
            self._inflight.pop(ref_hex, None)
            now = time.monotonic()
            self._last_result = now
            self._last_activity = now
            self._stalled = False  # results flow again
            self._cv.notify()
        # a freed pipeline slot may unblock the next window
        self._loop.wake(self)

    def take_inflight(self, ref_hex: str) -> Optional[dict]:
        """Pop one in-flight item (worker handed it back never-started);
        the caller re-routes it. Frees the pipeline slot like a result."""
        with self._cv:
            item = self._inflight.pop(ref_hex, None)
            if item is not None:
                self._cv.notify()
        if item is not None:
            self._loop.wake(self)
        return item

    def cancel(self, ref_hex: str) -> bool:
        """Best-effort cancel of a not-yet-running leased task: local
        queue first, then a targeted recall of the worker's queue."""
        with self._cv:
            for it in self._q:
                if it["ref"] == ref_hex:
                    self._q.remove(it)
                    return True
            owed = ref_hex in self._inflight
        if not owed or self.dead:
            return False
        try:
            reply = self._worker.call(
                "LeaseRecall",
                {"lease_id": self.lease_id, "refs": [ref_hex]},
                timeout=10.0,
            )
        except RpcError:
            return False
        if ref_hex in (reply.get("removed") or ()):
            with self._cv:
                self._inflight.pop(ref_hex, None)
                self._cv.notify()
            return True
        return False

    def kill_running(self, ref_hex: str) -> bool:
        """Force-cancel the currently executing leased task by killing
        its worker (head-path force semantics). The worker's death trips
        the normal fail-over; the caller pre-seals the cancel so the
        death spill skips this ref."""
        if self.dead or ref_hex not in self._inflight:
            return False
        try:
            reply = self._worker.call(
                "LeaseKillRunning",
                {"lease_id": self.lease_id, "ref": ref_hex},
                timeout=10.0,
            )
        except RpcError:
            return False
        return bool(reply.get("ok"))

    def step(self, now: float) -> Optional[float]:
        """Fused-loop callback: inspect state, pick ONE action (send a
        whole window / retire / recall / probe / renew), and offload its
        blocking RPC to the sender pool. At most one action in flight per
        channel — the per-lease ordering the worker FIFO expects."""
        action = None
        batch: List[dict] = []
        with self._cv:
            if self.dead:
                return None
            if not self._busy:
                window = self.max_inflight - len(self._inflight)
                if self._q and window > 0 and not self._stalled:
                    action = "send"
                elif not self._q and not self._inflight:
                    if now - self._last_activity > self.ttl:
                        self.dead = True
                        action = "retire"
                elif self._inflight:
                    quiet = now - max(self._last_result, self._last_send)
                    # stall budget scales with the outstanding window
                    # (~stall_s of sequential execution per owed
                    # task, capped): a deep pipeline draining slowly
                    # on a loaded host is NOT a wedge — a flat
                    # threshold spilled flowing work in cascades —
                    # while a blocked head-of-line with a few
                    # followers (rendezvous peers) still recalls in
                    # a few seconds
                    budget = min(
                        self._stall_s * max(1, len(self._inflight)),
                        10.0 * self._stall_s,
                    )
                    if quiet > budget and (
                        len(self._inflight) > 1 or self._q
                    ):
                        action = "recall"
                    elif quiet > 5.0 and now - self._last_probe > 5.0:
                        action = "probe"
                if action is None and self._renew_due(now):
                    action = "renew"
                if action == "send":
                    n = min(
                        self.MAX_BATCH,
                        self.max_inflight - len(self._inflight),
                    )
                    while self._q and len(batch) < n:
                        it = self._q.popleft()
                        self._inflight[it["ref"]] = it
                        batch.append(it)
                    self._last_send = time.monotonic()
                if action is not None:
                    self._busy = True
        if action is not None:
            if batch:
                self._loop.note_batch(len(batch))
            if not self._loop.offload(self, self._run_action, action, batch):
                # sender pool gone (runtime shutdown): put the popped
                # window back at the FRONT of the queue (a stranded
                # in-flight set would hang its callers' gets), clear the
                # busy flag, and for a retire finish the bookkeeping
                # inline (no RPC involved)
                with self._cv:
                    for it in reversed(batch):
                        self._inflight.pop(it["ref"], None)
                        self._q.appendleft(it)
                    self._busy = False
                if action == "retire":
                    self._teardown(spill=False)
                    return None
        # the 0.25s tick the per-channel thread used to poll at — now one
        # timer entry on the shared loop instead of a parked thread each
        return now + 0.25

    def _run_action(self, action: str, batch: List[dict]) -> None:
        rt = self._rt
        try:
            if action == "retire":
                self._teardown(spill=False)
                return
            if action == "send":
                req = {
                    "lease_id": self.lease_id,
                    "client_addr": rt._callback_address(),
                    "items": [
                        {
                            k: v
                            for k, v in it.items()
                            if not k.startswith("_")
                        }
                        for it in batch
                    ],
                }
                if self.accel_env:
                    req["accel_env"] = self.accel_env
                t0 = time.perf_counter()
                accepts = self._worker.call(
                    "LeaseTaskBatch", req, timeout=60.0
                )
                # one observe per WINDOW: the per-item share of the send
                DISPATCH_OVERHEAD_US.observe(
                    (time.perf_counter() - t0) * 1e6 / max(1, len(batch)),
                    {"stage": "wire"},
                )
                rejected = []
                released = False
                with self._cv:
                    for it, status in zip(batch, accepts):
                        if status != "accepted":
                            self._inflight.pop(it["ref"], None)
                            rejected.append(it)
                            released = released or status == "released"
                for it in rejected:
                    rt._lease_spill(it)
                if released:
                    # "released" is lease-level, not per-item: the
                    # worker-side lease is gone for good — a channel
                    # left alive would absorb every future same-shape
                    # task into a worker-RPC-then-spill loop
                    self._drain_then_fail()
                    return
            elif action == "recall":
                # head-of-line wedged: pull queued work back and let
                # the head place it on other workers; the running
                # task keeps its slot until it completes
                reply = self._worker.call(
                    "LeaseRecall", {"lease_id": self.lease_id},
                    timeout=10.0,
                )
                recalled: List[dict] = []
                with self._cv:
                    for ref in reply.get("removed") or ():
                        it = self._inflight.pop(ref, None)
                        if it is not None:
                            recalled.append(it)
                    recalled.extend(self._q)
                    self._q.clear()
                    self._stalled = True  # until a result arrives
                for it in recalled:
                    rt._lease_spill(it)
            elif action == "probe":
                # small retry budget: a loaded-but-alive worker must
                # not fail the whole lease over one slow ping (a
                # spurious fail_over ERRORS max_retries=0 tasks)
                self._worker.call("Ping", timeout=5.0, retries=2)
                self._last_probe = time.monotonic()
            if action in ("send", "recall", "renew"):
                self._maybe_renew()
        except RpcError:
            if batch:
                # the batch whose SEND failed was (almost certainly)
                # never delivered: respill it as never-started —
                # at-least-once for mid-flight batches, the
                # _DirectActorChannel convention. Only items a
                # PREVIOUS batch delivered can be mid-execution;
                # _fail_over labels those may-have-run.
                with self._cv:
                    for it in batch:
                        self._inflight.pop(it["ref"], None)
                for it in batch:
                    rt._lease_spill(it)
            self._fail_over()
            return
        finally:
            with self._cv:
                self._busy = False

    def _renew_due(self, now: float) -> bool:
        return (
            bool(self._q or self._inflight)
            and now - self._last_renew >= self.ttl / 2.0
        )

    def _maybe_renew(self) -> None:
        now = time.monotonic()
        if now - self._last_renew >= self.ttl / 2.0:
            self._last_renew = now
            self._rt._sender.enqueue(
                "lease_renew",
                {
                    "lease_ids": [self.lease_id],
                    "client_id": self._rt.client_id,
                },
            )

    def on_killed(self) -> None:
        """We deliberately killed the leased worker (force-cancel of its
        running task). The FIFO is sequential, so nothing else was
        executing: every other unresolved item is never-started by
        construction and respills; the pre-sealed victim is skipped by
        the spill idempotence guard."""
        with self._cv:
            if self.dead:
                return
            self.dead = True
            items = list(self._inflight.values())
            self._inflight.clear()
            queued = list(self._q)
            self._q.clear()
        seen = set()
        for it in items + queued:
            if it["ref"] not in seen:
                seen.add(it["ref"])
                self._rt._lease_spill(it)
        self._teardown(spill=False)

    def _drain_then_fail(self) -> None:
        """The lease was released under us but the WORKER is alive: it
        is pushing 'spill' results for the items it never started and
        the running item's real result. Those pushes — not our local
        guess — decide never-started vs may-have-run, so wait for the
        in-flight set to drain before failing over whatever never
        arrived (a lost push, rare). Racing _fail_over immediately used
        to mislabel ~a whole window of never-started max_retries=0
        tasks as may-have-run and permanently fail them."""
        with self._cv:
            self.dead = True
            deadline = time.monotonic() + 5.0
            while self._inflight and time.monotonic() < deadline:
                self._cv.wait(timeout=0.25)
        self._fail_over()

    def _fail_over(self) -> None:
        """Worker unreachable: everything unresolved re-routes through
        head scheduling, and the lease is returned so a still-alive
        worker behind a transient partition is unpinned."""
        with self._cv:
            self.dead = True
            items = list(self._inflight.values())
            self._inflight.clear()
            queued = list(self._q)
            self._q.clear()
        seen = set()
        for it in items:
            if it["ref"] not in seen:
                seen.add(it["ref"])
                # in-flight at failure: the worker MAY have started it
                self._rt._lease_spill(it, may_have_run=True)
        for it in queued:
            if it["ref"] not in seen:
                seen.add(it["ref"])
                self._rt._lease_spill(it)
        self._teardown(spill=False)

    def _teardown(self, spill: bool) -> None:
        if spill:
            self._fail_over()
            return
        self._loop.unregister(self)
        self._mgr._drop_channel(self.shape_key, self)
        self._rt._drop_direct_channel(self.key, self)
        try:
            self._rt._sender.enqueue(
                "lease_return",
                {"lease_id": self.lease_id, "node_id": self.node_id},
            )
        except Exception:  # noqa: BLE001 - sender already stopped
            pass
        try:
            self._worker.close()
        except Exception:  # noqa: BLE001
            pass

    def stop(self) -> None:
        """Shutdown path: spill nothing (the runtime is going away), but
        hand the lease back so the worker returns to its pool."""
        with self._cv:
            if self.dead:
                return
            self.dead = True
            self._cv.notify_all()
        self._teardown(spill=False)

    def __repr__(self) -> str:  # debug surfaces
        return (
            f"_TaskLeaseChannel({self.lease_id[:8]}, depth={self.depth()})"
        )


class _TaskLeaseManager:
    """Owner-side lease cache keyed by task shape (fn hash x resource
    demand x runtime-env signature). A shape turns hot on its second
    submission (one-off tasks never pin workers); the cache then grows
    one lease at a time — up to ``task_lease_max_per_shape`` — while its
    queues run deeper than one pipeline window. Tasks that find no
    accepting lease take the per-task head path (a miss, never a
    stall)."""

    WARMUP = 2  # misses before the first grant request for a shape

    def __init__(self, runtime: "RemoteRuntime"):
        from ray_tpu.config import cfg

        self._rt = runtime
        self._lock = threading.Lock()
        self._shapes: Dict[tuple, dict] = {}
        self._stopped = False
        self.max_inflight = max(1, int(cfg.task_lease_max_inflight))
        self.max_per_shape = max(1, int(cfg.task_lease_max_per_shape))
        self.stall_s = float(cfg.task_lease_stall_s)
        # local queueing beyond this overflows to the head path instead —
        # a memory/latency bound, not a throughput lever: queued items
        # cost one dict entry each, lease loss spills them, and the stall
        # recall pulls them off a wedged worker, so the bound can sit
        # well above the pipeline window (a submit burst should ride the
        # leases it warmed, not fall off them)
        self.queue_cap = 16 * self.max_inflight

    def submit(self, item: dict, shape_key: tuple) -> bool:
        """True = streamed to a cached lease (caller is done); False =
        no usable lease (caller takes the head path)."""
        rt = self._rt
        with self._lock:
            if self._stopped:
                return False
            ent = self._shapes.get(shape_key)
            if ent is None:
                if len(self._shapes) > 512:
                    # cold-shape pruning: drivers minting closures in a
                    # loop get a fresh fn_id (and shape entry) each time —
                    # entries with no lease and no grant in flight are
                    # just counters and can go
                    now = time.monotonic()
                    for k in list(self._shapes):
                        e = self._shapes[k]
                        if (
                            not e["channels"]
                            and not e["granting"]
                            and now > e["cooldown_until"]
                        ):
                            del self._shapes[k]
                ent = self._shapes[shape_key] = {
                    "channels": [],
                    "granting": 0,
                    "cooldown_until": 0.0,
                    "misses": 0,
                    "resources": dict(item["_resources"]),
                    "fn_id": item["fn_id"],
                }
            chans = [c for c in ent["channels"] if not c.dead]
            if len(chans) != len(ent["channels"]):
                ent["channels"] = chans
            chan = None
            for c in chans:
                if c.accepting() and c.depth() < self.queue_cap:
                    if chan is None or c.depth() < chan.depth():
                        chan = c
            if chan is None:
                ent["misses"] += 1
                if ent["misses"] >= self.WARMUP or chans:
                    self._maybe_grant_locked(ent, shape_key)
            elif (
                chan.depth() >= self.max_inflight
                and len(chans) + ent["granting"] < self.max_per_shape
            ):
                # one full pipeline window queued: grow while we stream
                self._maybe_grant_locked(ent, shape_key)
        if chan is None:
            rt.metrics["lease_cache_misses"] += 1
            LEASE_CACHE_MISSES.inc()
            return False
        rt.metrics["lease_cache_hits"] += 1
        LEASE_CACHE_HITS.inc()
        # pin args + register the pending ref BEFORE the channel sees the
        # item (same contract as direct actor calls: the result handler
        # releases these)
        from ray_tpu.core.refcount import TRACKER

        ids = item["arg_ids"]
        with rt._direct_cv:
            for h in ids:
                TRACKER.incref(h)
            rt._direct_pending[item["ref"]] = chan.key
            if ids:
                rt._direct_arg_pins[item["ref"]] = ids
        chan.submit(item)
        return True

    def _maybe_grant_locked(self, ent: dict, shape_key: tuple) -> None:
        """Caller holds self._lock."""
        if self._stopped:
            return
        if len(ent["channels"]) + ent["granting"] >= self.max_per_shape:
            return
        if time.monotonic() < ent["cooldown_until"]:
            return
        ent["granting"] += 1
        threading.Thread(
            target=self._grant,
            args=(shape_key, dict(ent["resources"]), ent["fn_id"]),
            name="lease-grant",
            daemon=True,
        ).start()

    def _grant(self, shape_key: tuple, resources: dict, fn_id: str) -> None:
        reply = None
        try:
            reply = self._rt.head.call(
                "GrantTaskLease",
                {
                    "resources": resources,
                    "fn_id": fn_id,
                    "client_id": self._rt.client_id,
                    "timeout": 10.0,
                },
                timeout=40.0,
                epoch=self._rt._cluster_epoch,
            )
        except RpcStaleEpochError:
            # fenced by a rebuilt head: resync (fresh hello) and let the
            # cooldown retry the grant with the new epoch
            try:
                self._rt._hello()
            except Exception:  # noqa: BLE001
                pass
        except Exception:  # noqa: BLE001 - head unreachable: cooldown
            pass
        dangling = None  # granted after the runtime stopped: hand it back
        with self._lock:
            ent = self._shapes.get(shape_key)
            if ent is not None:
                ent["granting"] -= 1
            if self._stopped and reply and reply.get("granted"):
                dangling = reply
                reply = None
            if ent is None:
                pass
            elif reply and reply.get("granted"):
                chan = _TaskLeaseChannel(self._rt, self, shape_key, reply)
                ent["channels"].append(chan)
            else:
                ent["cooldown_until"] = time.monotonic() + 2.0
        if dangling is not None:
            try:
                self._rt._sender.enqueue(
                    "lease_return",
                    {
                        "lease_id": dangling["lease_id"],
                        "node_id": dangling.get("node_id"),
                    },
                )
            except Exception:  # noqa: BLE001 - sender stopped too
                pass

    def _drop_channel(self, shape_key: tuple, chan) -> None:
        with self._lock:
            ent = self._shapes.get(shape_key)
            if ent is not None and chan in ent["channels"]:
                ent["channels"].remove(chan)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True


class _ResultSink:
    """DirectResults delivery fused onto the runtime's event loop.

    RPC handler threads enqueue whole windows here and return
    immediately; the loop's ``step`` drains EVERY queued window in one
    pass and offloads the merged batch to the sender pool — one wake per
    window burst instead of one lock hop per push (the third thread
    family the fused loop absorbs, next to lease windows and direct
    pushes). Processing is offloaded, never run on the loop thread:
    ``_process_direct_results`` can owe a blocking head RPC (owner-held
    upload on eviction), and the loop's contract is non-blocking steps.
    At most one processing action is in flight, so batches stay FIFO
    (a worker's pushes must not reorder)."""

    def __init__(self, rt: "RemoteRuntime"):
        from concurrent.futures import ThreadPoolExecutor

        self._rt = rt
        self._batches: deque = deque()
        self._busy = False
        self._lock = threading.Lock()
        # DEDICATED delivery thread: result processing must never queue
        # behind 60s-blocking sends on the shared sender pool — during a
        # mass lease-revoke storm a starved drain would let
        # _drain_then_fail time out and mislabel never-started
        # max_retries=0 windows as may-have-run
        self._exec = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="hotpath-results"
        )
        rt._hotloop.register(self)

    def push(self, results: List[dict]) -> None:
        self._batches.append(results)
        if self._rt._hotloop.wake(self):
            return
        # loop stopped (shutdown): drain inline so late pushes still land
        self._drain_inline()

    def _drain_inline(self) -> None:
        while True:
            with self._lock:
                if self._busy or not self._batches:
                    return
                batches: List[list] = []
                while self._batches:
                    batches.append(self._batches.popleft())
                self._busy = True
            self._run(batches)

    def step(self, now: float) -> None:
        with self._lock:
            if self._busy or not self._batches:
                return None
            batches: List[list] = []
            while self._batches:
                batches.append(self._batches.popleft())
            self._busy = True
        try:
            self._exec.submit(self._run_and_rewake, batches)
        except RuntimeError:  # executor closed (shutdown): inline
            self._run(batches)
        return None

    def _run_and_rewake(self, batches: List[list]) -> None:
        self._run(batches)
        # windows pushed while we were processing re-enter via the loop
        self._rt._hotloop.wake(self)

    def close(self) -> None:
        self._exec.shutdown(wait=False)

    def _run(self, batches: List[list]) -> None:
        try:
            merged: List[dict] = []
            for b in batches:
                merged.extend(b)
            if merged:
                self._rt._hotloop.note_batch(len(merged))
                self._rt._process_direct_results(merged)
        finally:
            with self._lock:
                self._busy = False
        # a push that raced in while we were busy AND the loop died has
        # nobody left to wake us: sweep it up before returning
        if self._batches and not self._rt._hotloop.alive():
            self._drain_inline()


class RemoteActorHandle:
    def __init__(self, runtime: "RemoteRuntime", actor_id: str, cls: type):
        self._runtime = runtime
        self._actor_id = actor_id
        self._cls = cls

    def __getattr__(self, name: str):
        # "__call__" is a legitimate remote method (serve deployments
        # dispatch it); every other underscore name stays an attribute
        # error so pickling/introspection behave
        if name.startswith("_") and name != "__call__":
            raise AttributeError(name)
        return _RemoteMethod(self._runtime, self._actor_id, name)

    def __reduce__(self):
        return (_rebuild_actor_handle, (self._actor_id, self._cls))


def _rebuild_actor_handle(actor_id: str, cls: type):
    from ray_tpu.core.runtime import get_runtime

    return RemoteActorHandle(get_runtime(), actor_id, cls)


class _RemoteMethod:
    def __init__(
        self,
        runtime: "RemoteRuntime",
        actor_id: str,
        method: str,
        num_returns=1,
    ):
        self._runtime = runtime
        self._actor_id = actor_id
        self._method = method
        self._num_returns = num_returns

    def options(self, num_returns=None, **_ignored) -> "_RemoteMethod":
        return _RemoteMethod(
            self._runtime,
            self._actor_id,
            self._method,
            num_returns or self._num_returns,
        )

    def remote(self, *args, **kwargs):
        if self._num_returns == "streaming":
            return self._runtime.submit_actor_method_streaming(
                self._actor_id, self._method, args, kwargs
            )
        return self._runtime.submit_actor_method(
            self._actor_id, self._method, args, kwargs
        )


class _PipelinedSender:
    """Client→head submission pipeline (the reference's task-submission
    pipelining, core_worker/task_submission/normal_task_submitter.h): lease
    submissions and refcount updates ride ONE ordered queue, coalesced into
    ``ClientBatch`` RPCs. An idle sender ships immediately (no added
    latency); under load everything queued while the previous RPC was in
    flight merges into one message. Ordering between a submission that
    registers return-id holders and a later release of those ids is
    preserved by construction."""

    MAX_BATCH = 512

    def __init__(self, client: RpcClient, epoch_fn=None, on_stale=None):
        self._client = client
        # epoch-fenced control plane: epoch_fn supplies the stamp for
        # every ClientBatch; on_stale runs the owner-side resync (a fresh
        # ClientHello) when a rebuilt head rejects our stamp
        self._epoch_fn = epoch_fn
        self._on_stale = on_stale
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._enqueued = 0
        self._acked = 0
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name="lease-pipeline", daemon=True
        )
        self._thread.start()

    def enqueue(
        self,
        kind: str,
        payload: Any,
        wait: bool = False,
        wait_timeout: Optional[float] = None,
    ) -> None:
        """Queue one control item. ``wait=True`` blocks until the head has
        processed it; ``wait_timeout`` bounds that wait — on expiry an
        RpcError raises (the item STAYS queued and delivers when the head
        returns; only this caller's synchronous view gives up)."""
        with self._cv:
            if self._stop:
                return
            self._q.append((kind, payload))
            self._enqueued += 1
            ticket = self._enqueued
            self._cv.notify_all()
        if wait:
            deadline = (
                None
                if wait_timeout is None
                else time.monotonic() + wait_timeout
            )
            with self._cv:
                while self._acked < ticket and not self._stop:
                    if (
                        deadline is not None
                        and time.monotonic() >= deadline
                    ):
                        raise RpcError(
                            f"head unreachable: {kind} not acknowledged "
                            f"within {wait_timeout}s (still queued)"
                        )
                    self._cv.wait(timeout=0.5)

    def enqueue_many(self, kind: str, payloads: List[Any]) -> None:
        """Queue a window of same-kind control items under one lock pass
        (ordered with everything else on the pipeline)."""
        with self._cv:
            if self._stop:
                return
            for p in payloads:
                self._q.append((kind, p))
            self._enqueued += len(payloads)
            self._cv.notify_all()

    def rebind(self, client: RpcClient) -> None:
        """Swap the underlying channel (head failover): the loop reads
        ``self._client`` per attempt, so queued items redeliver to the
        new leader in order."""
        with self._cv:
            self._client = client
            self._cv.notify_all()

    def try_enqueue_once(self, kind: str, payload: Any, prev_ticket: int) -> int:
        """Queue one item unless the previous such item is still
        undelivered (heartbeats must not pile up behind a head outage).
        Returns the new ticket, or ``prev_ticket`` when skipped."""
        with self._cv:
            if self._stop or prev_ticket > self._acked:
                return prev_ticket
            self._q.append((kind, payload))
            self._enqueued += 1
            self._cv.notify_all()
            return self._enqueued

    def _loop(self) -> None:
        import logging

        log = logging.getLogger("ray_tpu.cluster.client")
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(timeout=0.5)
                if not self._q:
                    if self._stop:
                        return
                    continue
                n = min(len(self._q), self.MAX_BATCH)
                batch = [self._q.popleft() for _ in range(n)]
            delivered = False
            attempts = 0
            while not delivered:
                try:
                    attempts += 1
                    if attempts == 2 or attempts % 60 == 0:
                        log.warning(
                            "ClientBatch re-send #%d (%d items)",
                            attempts,
                            len(batch),
                        )
                    self._client.call(
                        "ClientBatch",
                        batch,
                        timeout=60.0,
                        retries=8,
                        retry_interval=0.25,
                        epoch=(
                            self._epoch_fn() if self._epoch_fn else None
                        ),
                    )
                    delivered = True
                except (RpcStaleEpochError, RpcNotLeaderError):
                    # the head restarted (stale epoch) or fenced itself
                    # behind a promoted standby (not leader): run the
                    # owner resync — a fresh ClientHello adopts the new
                    # epoch and, on failover, rebinds this sender to the
                    # new leader — then redeliver this same batch; order
                    # preserved, nothing dropped
                    import sys

                    if sys.is_finalizing():
                        return
                    log.warning(
                        "head epoch advanced; re-helloing before re-send"
                    )
                    if self._on_stale is not None:
                        try:
                            self._on_stale()
                        except Exception:  # noqa: BLE001 - retried below
                            pass
                    with self._cv:
                        if self._stop:
                            return
                        self._cv.wait(timeout=0.2)
                except (RpcError, RuntimeError):
                    # a dropped lease would strand its caller's get()
                    # forever and a dropped release leaks the object —
                    # keep the batch and retry until the head comes back
                    # (or this runtime shuts down). RuntimeError: the
                    # channel's executor closed under us (shutdown race) —
                    # same stop checks apply, never an unhandled thread
                    # exception.
                    import sys

                    if sys.is_finalizing():
                        return  # interpreter exit: nobody to deliver for
                    if attempts <= 2 or attempts % 60 == 0:
                        log.warning(
                            "head unreachable; retrying %d control items",
                            len(batch),
                        )
                    # event-driven pause (the long-poll pattern the rest
                    # of the client uses, e.g. wait_many): park on the
                    # queue's condition variable so a stop() — or new
                    # work signalling the head may be back — wakes the
                    # retry immediately instead of sleeping blind.
                    with self._cv:
                        if self._stop:
                            return
                        self._cv.wait(timeout=0.5)
            with self._cv:
                self._acked += len(batch)
                self._cv.notify_all()

    def drain(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        with self._cv:
            target = self._enqueued
            while self._acked < target and time.monotonic() < deadline:
                self._cv.wait(timeout=0.2)

    def stop(self) -> None:
        self.drain()
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        # join BEFORE the caller closes the rpc channel: an in-flight send
        # racing the channel's executor shutdown was the
        # cannot-schedule-new-futures stray-thread exception the full
        # suite used to end with
        self._thread.join(timeout=5.0)


class RemoteRuntime:
    """Duck-typed Runtime whose backend is a live cluster."""

    is_remote = True

    def __init__(self, address: str, runtime_env: Optional[dict] = None):
        # ``address`` may be a comma list (primary + warm standbys); the
        # candidate walk also folds in RAY_TPU_HEAD_STANDBYS. With more
        # than one candidate, connect to whichever currently leads.
        from .rpc import head_candidates, probe_leader

        self._head_candidates = head_candidates(address)
        if len(self._head_candidates) > 1:
            found = probe_leader(self._head_candidates, timeout=2.0)
            if found is not None:
                address = found[0]
            else:
                address = self._head_candidates[0]
        self.address = address
        self.head = RpcClient(address)
        self.head.call("Ping", timeout=10.0, retries=20, retry_interval=0.25)
        self.runtime_env = runtime_env
        self._agents: Dict[str, RpcClient] = {}
        self._lock = threading.Lock()
        self.store = _RemoteStore(self)
        self.metrics: Dict[str, int] = {}
        # distributed refcounting: this process's holder identity + release
        # reporter. Inside a cluster worker the worker's flusher (which
        # routes via its agent) is already installed and is reused.
        from ray_tpu.core import refcount

        self.client_id = refcount.get_holder_id()
        # peer-leased data links for big-object pulls (transport.py),
        # lazily built on the first located fetch
        self._peer_links = None
        # direct actor calls: per-actor submission channels straight to the
        # hosting worker; results arrive on a lazily-started callback
        # server. RAY_TPU_DIRECT_ACTOR_CALLS=0 forces everything through
        # the head-scheduled lease path.
        from ray_tpu.config import cfg

        self._direct_enabled = cfg.direct_actor_calls
        # hot-path cfg snapshot: these flags are read per submission /
        # per awaited ref, and cfg reads consult os.environ live. Set the
        # env before connect() to change them for a runtime.
        self._trace_autostart = cfg.trace_tasks
        self._direct_wait_fallback_s = cfg.direct_wait_fallback_s
        # lease-cached direct task dispatch (RAY_TPU_TASK_LEASES=0 kills
        # it: every task rides the per-task head path). Leased-task
        # results arrive on the same push channel as direct actor calls,
        # so the result-cache paths check the union flag.
        self._lease_enabled = cfg.task_leases
        self._push_enabled = self._direct_enabled or self._lease_enabled
        # the fused submit/result event loop: lease channels, direct
        # actor channels, and result delivery are all sources on this ONE
        # loop (its thread starts lazily on first registration)
        from .event_loop import FusedEventLoop

        self._hotloop = FusedEventLoop(
            name="hotpath", senders=int(cfg.hotpath_senders)
        )
        self._result_sink = _ResultSink(self)
        self._lease_mgr = (
            _TaskLeaseManager(self) if self._lease_enabled else None
        )
        # shape-key env signature for the runtime-level env, computed
        # once (per-task envs are rare; the runtime env applies to every
        # submission and must not be re-serialized per task)
        import json as _json

        self._base_env_sig = (
            _json.dumps(self.runtime_env, sort_keys=True, default=str)
            if self.runtime_env
            else None
        )
        self.metrics.update(
            lease_cache_hits=0,
            lease_cache_misses=0,
            lease_spillbacks=0,
            lineage_resubmits=0,
        )
        # owner-side lineage (ownership-model reconstruction): leased
        # direct-dispatch tasks never register a spec with the head, so
        # the owner retains each task's submit item keyed by return ref
        # and resubmits through head scheduling when the head seals the
        # object lost-without-lineage. Byte-bounded LRU — an evicted
        # object's loss is permanent.
        from collections import OrderedDict as _OrderedDict

        self._lineage_lock = threading.Lock()
        self._lineage: "_OrderedDict[str, dict]" = _OrderedDict()
        self._lineage_bytes = 0
        # one cloudpickle of each task function per function OBJECT (weak:
        # dead lambdas drop their blobs); see _serialize_fn
        import weakref

        self._fn_blobs: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        self._direct_channels: Dict[str, _DirectActorChannel] = {}
        self._direct_results: Dict[str, tuple] = {}  # hex -> (kind, payload)
        # FIFO bound on the local result cache: fire-and-forget callers
        # never get() their refs, and every result also reached the head's
        # directory — evicted entries just resolve through the head
        self._direct_results_order: deque = deque()
        self._direct_results_cap = cfg.direct_results_cap
        self._direct_pending: Dict[str, str] = {}  # hex -> actor_id
        # streaming generators: task_id -> (base_index, [item ids], done)
        self._stream_cache: Dict[str, tuple] = {}
        self._direct_arg_pins: Dict[str, List[str]] = {}  # hex -> arg ids
        # owner-held results (cfg.direct_deferred_seals): hex -> contained
        # ids; the head learns about these objects only on share/evict
        self._deferred_seals: Dict[str, List[str]] = {}
        # refs shared into another submission BEFORE their direct result
        # arrived: the arrival handler uploads these instead of deferring
        self._shared_pending: set = set()
        self._direct_cv = threading.Condition()
        self._callback_server: Optional[RpcServer] = None
        # --- owner session + epoch-fenced control plane -----------------
        # a DRIVER process (one installing its own flusher below) holds a
        # session lease with the head: it heartbeats on the pipelined
        # ClientBatch, and a crashed driver is reaped (actors killed,
        # leases revoked, unproduced objects failed with OwnerDiedError).
        # Worker-embedded runtimes reuse the worker's identity and fate-
        # share through the agent's worker-death reports instead.
        self._stop_event = threading.Event()
        self._shutdown_done = False
        self._beat_ticket = 0
        self._cluster_epoch: Optional[int] = None
        self._owner_ttl_s = float(cfg.owner_lease_ttl_s)
        self._owner_session = bool(cfg.owner_liveness) and not isinstance(
            refcount.current_consumer(), refcount.RefFlusher
        )
        self._hello()
        # dedicated channel for the pipeline: its traffic during a head
        # outage must not push the main channel into gRPC reconnect backoff
        self._pipe_chan = RpcClient(address)
        self._sender = _PipelinedSender(
            self._pipe_chan,
            epoch_fn=lambda: self._cluster_epoch,
            on_stale=self._hello,
        )
        incumbent = refcount.current_consumer()
        if isinstance(incumbent, refcount.RefFlusher):
            self._flusher = incumbent
            self._owns_flusher = False
        else:
            # _ref_wait_timeout bounds the synchronous ack wait on ref
            # updates: None (wait out the head) in steady state; shutdown
            # sets it so the exit path can NEVER hang on a wedged
            # pipeline (the item stays queued either way, and the head's
            # disconnect reap drops our holder rows regardless)
            self._ref_wait_timeout: Optional[float] = None
            self._flusher = refcount.RefFlusher(
                lambda inc, dec: self._sender.enqueue(
                    "ref",
                    {"holder": self.client_id, "increfs": inc, "decrefs": dec},
                    wait=True,
                    wait_timeout=self._ref_wait_timeout,
                ),
                holder=self.client_id,
            )
            refcount.install_consumer(self._flusher)
            self._owns_flusher = True
        if self._owner_session:
            threading.Thread(
                target=self._owner_beat_loop, name="owner-beat", daemon=True
            ).start()
        # best-effort bounded shutdown at interpreter exit: a driver that
        # never calls shutdown()/exits a with-block still sends its
        # DisconnectClient instead of falling through to crash detection
        import atexit
        import weakref

        ref = weakref.ref(self)

        def _exit_hook(_ref=ref):
            rt = _ref()
            if rt is not None:
                try:
                    rt.shutdown()
                except Exception:  # noqa: BLE001 - exit path
                    pass

        self._atexit_hook = _exit_hook
        atexit.register(_exit_hook)

    def __enter__(self) -> "RemoteRuntime":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def _hello(self) -> None:
        """ClientHello handshake: adopt the cluster epoch this runtime
        stamps its control stream with, and (driver processes) register
        the owner session lease. Re-run whenever a rebuilt head rejects
        our stamp as stale — re-hello IS the owner resync protocol. A
        NotLeader reply (the head fenced itself after a standby
        promoted elsewhere) walks the candidate list to the leader and
        re-hellos there."""
        try:
            reply = self.head.call(
                "ClientHello",
                {"client_id": self.client_id, "session": self._owner_session},
                timeout=10.0,
                retries=3,
                retry_interval=0.2,
            )
        except RpcNotLeaderError as exc:
            if not self._failover_head(exc.leader_hint):
                return
            try:
                reply = self.head.call(
                    "ClientHello",
                    {
                        "client_id": self.client_id,
                        "session": self._owner_session,
                    },
                    timeout=10.0,
                    retries=3,
                    retry_interval=0.2,
                )
            except Exception:  # noqa: BLE001 - next resync retries
                return
        except Exception:  # noqa: BLE001 - unstamped traffic still flows
            return
        self._cluster_epoch = reply.get("epoch")
        ttl = reply.get("owner_ttl_s")
        if ttl:
            self._owner_ttl_s = float(ttl)
        if not reply.get("owner_liveness", True):
            self._owner_session = False

    def _failover_head(self, hint: str = "") -> bool:
        """Walk the head-candidate list (rpc.resolve_leader) to the
        current leader; swap the control channels there. The pipelined
        sender rebinds in place — queued control items redeliver to the
        new leader in order, nothing dropped."""
        from .rpc import resolve_leader

        addr = resolve_leader(
            self.address, hint, ",".join(self._head_candidates)
        )
        if addr is None:
            return False
        if addr == self.address:
            return True
        import logging

        logging.getLogger("ray_tpu.cluster.client").warning(
            "head leadership moved %s -> %s; re-pointing", self.address, addr
        )
        old_head, old_pipe = self.head, getattr(self, "_pipe_chan", None)
        self.address = addr
        self.head = RpcClient(addr)
        if old_pipe is not None:
            self._pipe_chan = RpcClient(addr)
            self._sender.rebind(self._pipe_chan)
        for chan in (old_head, old_pipe):
            if chan is not None:
                try:
                    chan.close()
                except Exception:  # noqa: BLE001
                    pass
        return True

    def _owner_beat_loop(self) -> None:
        """Heartbeat the owner session at half the lease TTL, riding the
        ordered ClientBatch pipeline. At most one beat is ever queued: a
        head outage must not pile beats behind the retry loop (delivery
        of anything on the pipeline proves liveness just as well)."""
        period = max(0.25, self._owner_ttl_s / 2.0)
        while not self._stop_event.wait(period):
            sender = self._sender
            self._beat_ticket = sender.try_enqueue_once(
                "owner_beat",
                {"client_id": self.client_id},
                self._beat_ticket,
            )

    def _read(
        self,
        method: str,
        payload: Any = None,
        timeout: float = 30.0,
        deadline_s: Optional[float] = None,
    ):
        """Idempotent head reads retry through transport blips — a client
        rides through a head restart the way the reference's GCS client
        does (gcs_rpc_client.h retry budgets). ``deadline_s`` propagates a
        caller's overall budget: the retry loop never outlives it."""
        return self.head.call(
            method,
            payload,
            timeout=timeout,
            retries=8,
            retry_interval=0.25,
            deadline_s=deadline_s,
        )

    # ------------------------------------------------------------------
    # tasks
    # ------------------------------------------------------------------
    def _serialize_fn(self, fn) -> tuple:
        """Pickle a task function once per function object.

        Returns ``(blob, fn_id, fn_arg_ids, cacheable)``. Cached only when
        serialization collected zero ObjectRefs — a closure over a ref
        keeps per-call (de)serialization so ref lifetimes stay
        per-execution. Matches the reference's one-time function export
        (function_manager) vs. our previous per-call re-pickle: closure
        CELL mutations after first submission are not re-shipped, same as
        the reference."""
        from ray_tpu.core.refcount import collect_serialized

        try:
            ent = self._fn_blobs.get(fn)
        except TypeError:
            ent = None  # unhashable/unweakrefable callable
        if ent is not None:
            return ent
        _ship_module_by_value(fn)
        with collect_serialized() as ids:
            blob = cloudpickle.dumps(fn)
        fn_id = hashlib.blake2b(blob, digest_size=8).hexdigest()
        ent = (blob, fn_id, frozenset(ids), not ids)
        if not ids:
            try:
                self._fn_blobs[fn] = ent
            except TypeError:
                pass
        return ent

    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        from ray_tpu.core.refcount import collect_serialized

        fn_blob, fn_id, fn_arg_ids, fn_cacheable = self._serialize_fn(
            spec.func
        )
        sample = _sampled()
        if spec.args or spec.kwargs:
            t0 = time.perf_counter() if sample else 0.0
            with collect_serialized() as arg_ids:
                payload = wire.dumps((spec.args, spec.kwargs))
            if sample:
                DISPATCH_OVERHEAD_US.observe(
                    (time.perf_counter() - t0) * 1e6, {"stage": "serialize"}
                )
        else:
            # hot-path constant: argless tasks (control probes, noop-style
            # fan-out) share ONE precomputed payload — no per-call pickle,
            # no ref-collection context
            payload = _EMPTY_ARGS_PAYLOAD
            arg_ids = set()
        if fn_arg_ids:
            arg_ids |= fn_arg_ids
        deps = [a.hex for a in spec.args if isinstance(a, ObjectRef)]
        deps += [
            v.hex for v in spec.kwargs.values() if isinstance(v, ObjectRef)
        ]
        self._flush_deferred_seals(arg_ids)
        from ray_tpu.util import tracing

        trace = spec.trace or tracing.child_context(
            spec.task_id, self._trace_autostart
        )
        merged_env = (
            {**(self.runtime_env or {}), **spec.runtime_env}
            if spec.runtime_env
            else self.runtime_env
        )
        if self._lease_mgr is not None and self._leasable(spec, merged_env):
            item = {
                "task_id": spec.task_id,
                "ref": spec.returns[0].hex,
                "payload": payload,
                "arg_ids": sorted(arg_ids),
                "name": spec.name,
                "client_id": self.client_id,
                "trace": trace,
                "fn_blob": fn_blob,
                "fn_id": fn_id,
                "fn_cache": fn_cacheable,
                "runtime_env": merged_env,
                # client-local fields (stripped from the wire): enough to
                # rebuild a head-path LeaseRequest on spillback
                "_resources": dict(spec.resources),
                "_max_retries": spec.max_retries,
            }
            if spec.runtime_env:
                import json

                env_sig = json.dumps(
                    merged_env, sort_keys=True, default=str
                )
            else:
                env_sig = self._base_env_sig
            shape_key = (
                fn_id,
                tuple(sorted(spec.resources.items())),
                env_sig,
            )
            t0 = time.perf_counter() if sample else 0.0
            streamed = self._lease_mgr.submit(item, shape_key)
            if sample:
                DISPATCH_OVERHEAD_US.observe(
                    (time.perf_counter() - t0) * 1e6, {"stage": "enqueue"}
                )
            if streamed:
                # the head never sees this task's spec — WE are its
                # lineage (resubmitted on loss via _maybe_resubmit_lost).
                # max_retries=0 items never resubmit, so retaining their
                # lineage is pure per-task overhead: skip it.
                if spec.max_retries > 0:
                    self._note_lineage(item)
                return spec.returns
        lease = LeaseRequest(
            task_id=spec.task_id,
            name=spec.name,
            payload=payload,
            return_ids=[r.hex for r in spec.returns],
            resources=spec.resources,
            kind="task",
            max_retries=spec.max_retries,
            retry_exceptions=spec.retry_exceptions,
            strategy=spec.strategy,
            runtime_env=merged_env,
            arg_ids=sorted(arg_ids),
            deps=deps,
            client_id=self.client_id,
            trace=trace,
            fn_blob=fn_blob,
            fn_id=fn_id,
            fn_cache=fn_cacheable,
            streaming=bool(getattr(spec, "streaming", False)),
        )
        self._sender.enqueue("lease", lease)
        self._flusher.note_registered(lease.return_ids)
        return spec.returns

    @staticmethod
    def _leasable(spec: TaskSpec, merged_env: Optional[dict]) -> bool:
        """A task qualifies for lease-cached direct dispatch when nothing
        about it needs head-side routing or bookkeeping: no placement
        constraint, no top-level ObjectRef args (dependency-aware
        dispatch is the agent's job), a single return, no streaming, no
        exception-retry budget (worker/node-death retries are covered by
        spillback), and no pip/uv/conda env (those need dedicated
        interpreter workers)."""
        if spec.strategy is not None or getattr(spec, "streaming", False):
            return False
        if len(spec.returns) != 1 or spec.retry_exceptions:
            return False
        if any(isinstance(a, ObjectRef) for a in spec.args) or any(
            isinstance(v, ObjectRef) for v in spec.kwargs.values()
        ):
            return False
        if merged_env:
            from .pip_env import has_env

            if has_env(merged_env):
                return False
        return True

    def stream_next(
        self, task_id: str, index: int, timeout: Optional[float]
    ) -> Optional[ObjectRef]:
        """Long-poll the head for item ``index`` of a streaming-generator
        task (ObjectRefGenerator backend). None = stream ended before it.
        The ``after`` watermark doubles as the consumption ack that frees
        the executor's backpressure window."""
        cached = self._stream_cache.get(task_id)
        if cached is not None:
            base, ids, done = cached
            k = index - base
            if 0 <= k < len(ids):
                return ObjectRef(ids[k], owner=self.client_id)
            if done and k >= len(ids):
                self._stream_cache.pop(task_id, None)
                return None
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while True:
            wait_s = 2.0
            if deadline is not None:
                wait_s = min(wait_s, deadline - time.monotonic())
                if wait_s <= 0:
                    raise GetTimeoutError(
                        f"stream {task_id} item {index} not ready"
                    )
            reply = self._read(
                "WaitStream",
                {
                    "task_id": task_id,
                    "after": index,
                    "timeout": wait_s,
                    "holder": self.client_id,
                },
                timeout=wait_s + 15.0,
            )
            items = reply.get("items") or []
            done = bool(reply.get("done"))
            if items:
                # one long-poll returns every ready item; serve the rest
                # of the burst from this cache instead of an RPC per item.
                # Bounded: abandoned generators clear their entry via
                # stream_abandon; the cap catches pathological churn.
                if len(self._stream_cache) > 256:
                    self._stream_cache.pop(
                        next(iter(self._stream_cache)), None
                    )
                self._stream_cache[task_id] = (index, items, done)
                return ObjectRef(items[0], owner=self.client_id)
            if done:
                self._stream_cache.pop(task_id, None)
                return None

    def stream_abandon(self, task_id: str) -> None:
        """Best-effort consumer-drop notice (ObjectRefGenerator.__del__)."""
        self._stream_cache.pop(task_id, None)
        try:
            self.head.call("StreamAbandon", {"task_id": task_id}, timeout=5.0)
        except RpcError:
            pass

    def submit_actor_method(
        self, actor_id: str, method: str, args: tuple, kwargs: dict
    ) -> ObjectRef:
        # a batch of one: submit_actor_method_batch owns the single
        # implementation of item/lease construction and arg pinning
        return self.submit_actor_method_batch(
            actor_id, method, [(args, kwargs)]
        )[0]

    def submit_actor_method_batch(
        self, actor_id: str, method: str, calls: List[tuple]
    ) -> List[ObjectRef]:
        """Submit a WINDOW of calls to one actor in one pass: one
        pin/bookkeeping lock acquisition and one channel (or pipeline)
        wakeup for the whole batch — the ordered batch path PR 2 gave to
        actor creations/kills, extended to actor-task submission. The
        Data executor's actor pools dispatch per-actor block windows
        through this instead of per-block ``submit_actor_method``.

        ``calls`` is a sequence of ``(args, kwargs)``; returns one
        ObjectRef per call, in order.
        """
        from ray_tpu.core.refcount import TRACKER, collect_serialized

        from ray_tpu.util import tracing

        refs: List[ObjectRef] = []
        prepared: List[tuple] = []  # (ref, ids, item) | (ref, lease)
        for args, kwargs in calls:
            ref = ObjectRef.new(owner=actor_id)
            with collect_serialized() as arg_ids:
                payload = wire.dumps((method, args, kwargs))
            if arg_ids:
                self._flush_deferred_seals(arg_ids)
            ids = sorted(arg_ids)
            tid = new_id()
            refs.append(ref)
            if self._direct_enabled:
                item = {
                    "task_id": tid,
                    "actor_id": actor_id,
                    "ref": ref.hex,
                    "payload": payload,
                    "client_id": self.client_id,
                    "name": f"{actor_id[:8]}.{method}",
                    "arg_ids": ids,
                    "trace": tracing.child_context(
                        tid, self._trace_autostart
                    ),
                }
                prepared.append((ref, ids, item))
            else:
                prepared.append(
                    (
                        ref,
                        LeaseRequest(
                            task_id=tid,
                            name=f"{actor_id[:8]}.{method}",
                            payload=payload,
                            return_ids=[ref.hex],
                            resources={},
                            kind="actor_method",
                            actor_id=actor_id,
                            max_retries=0,
                            arg_ids=ids,
                            client_id=self.client_id,
                        ),
                    )
                )
        if not self._direct_enabled:
            self._flusher.note_registered([r.hex for r in refs])
            self._sender.enqueue_many(
                "lease", [lease for _, lease in prepared]
            )
            return refs
        # pin every arg (incl. refs nested in containers) until the
        # result lands: the worker registers its borrows synchronously
        # before replying, so our later release can never free an object
        # the actor still holds (the lease path gets this from head-side
        # arg pins; the direct path pins at the caller). Pinning happens
        # HERE, after every call in the window serialized successfully —
        # an incref taken per-call inside the prepare loop would leak for
        # calls 0..k-1 when call k's wire.dumps raises (nothing was
        # registered yet, so nothing would ever release them).
        with self._direct_cv:
            for ref, ids, _ in prepared:
                for h in ids:
                    TRACKER.incref(h)
                self._direct_pending[ref.hex] = actor_id
                if ids:
                    self._direct_arg_pins[ref.hex] = ids
        chan = self._direct_channels.get(actor_id)
        if chan is None:
            with self._lock:
                chan = self._direct_channels.get(actor_id)
                if chan is None:
                    chan = _DirectActorChannel(self, actor_id)
                    self._direct_channels[actor_id] = chan
        chan.submit_many([item for _, _, item in prepared])
        return refs

    def _submit_actor_lease(
        self,
        *,
        task_id: str,
        actor_id: str,
        name: str,
        payload: bytes,
        return_id: Optional[str],
        arg_ids: List[str],
        streaming: bool = False,
    ) -> None:
        lease = LeaseRequest(
            task_id=task_id,
            name=name,
            payload=payload,
            return_ids=[return_id] if return_id else [],
            resources={},
            kind="actor_method",
            actor_id=actor_id,
            max_retries=0,
            arg_ids=arg_ids,
            client_id=self.client_id,
            streaming=streaming,
        )
        self._sender.enqueue("lease", lease)

    def submit_actor_method_streaming(
        self, actor_id: str, method: str, args: tuple, kwargs: dict
    ):
        """num_returns="streaming" actor method: always the head-scheduled
        lease path (the direct channel replies once per call; a stream
        needs the per-item seal plumbing), yielding an
        ObjectRefGenerator like a streaming task."""
        from ray_tpu.core.object_store import ObjectRefGenerator
        from ray_tpu.core.refcount import collect_serialized

        with collect_serialized() as arg_ids:
            payload = wire.dumps((method, args, kwargs))
        if arg_ids:
            self._flush_deferred_seals(arg_ids)
        tid = new_id()
        self._submit_actor_lease(
            task_id=tid,
            actor_id=actor_id,
            name=f"{actor_id[:8]}.{method}",
            payload=payload,
            return_id=None,
            arg_ids=sorted(arg_ids),
            streaming=True,
        )
        return ObjectRefGenerator(tid, self)

    # ---- direct-call plumbing ----------------------------------------
    def _callback_address(self) -> str:
        with self._lock:
            if self._callback_server is None:
                self._callback_server = RpcServer(
                    {
                        "DirectResults": self._h_direct_results,
                        "Ping": lambda r: "pong",
                    },
                    port=0,
                    max_workers=4,
                )
            return self._callback_server.address

    def _h_direct_results(self, results: List[dict]) -> None:
        """DirectResults RPC handler: enqueue the window for the fused
        loop's result sink and return — the push RPC never waits on
        local processing, and bursts from many workers merge into one
        batch-at-once delivery pass."""
        self._result_sink.push(results)

    def _process_direct_results(self, results: List[dict]) -> None:
        from ray_tpu.core.refcount import TRACKER

        t_start = time.perf_counter()
        unpin: List[str] = []
        uploads: List[tuple] = []  # evicted owner-held objects → head
        register: List[str] = []  # head-sealed results: holder is on books
        spills: List[str] = []  # leased tasks handed back never-started
        with self._direct_cv:
            for r in results:
                h = r["ref"]
                if r.get("status") == "spill":
                    # lease released under a queued task: the worker
                    # never started it — re-route through the head
                    # (outside this lock; the channel still holds it)
                    spills.append(h)
                    continue
                if "deferred_seal" not in r:
                    # the worker sealed this one to the head (error, big
                    # value, ref-containing result, or deferred seals
                    # off): the seal registered us as holder, so a local
                    # release is owed — and any share-while-pending flag
                    # is moot (the head knows the object)
                    register.append(h)
                    self._shared_pending.discard(h)
                if r["status"] == "ok":
                    self._direct_results[h] = ("val", r["value"])
                    if "deferred_seal" in r:
                        contained = list(r["deferred_seal"] or ())
                        if h in self._shared_pending:
                            # the ref was already shared into another
                            # submission while the call ran: a consumer
                            # somewhere is dep-waiting on the head —
                            # upload now, don't defer
                            self._shared_pending.discard(h)
                            uploads.append((h, r["value"], contained))
                        else:
                            # ownership model: we (the caller) hold the
                            # only record of this object; the head learns
                            # about it on share or eviction
                            self._deferred_seals[h] = contained
                elif r["status"] == "error":
                    self._direct_results[h] = ("err", r["error"])
                else:
                    self._direct_results[h] = ("seal", r["seal"])
                self._direct_results_order.append(h)
                # lazy deque hygiene: drop heads already consumed by get()
                # (so the deque tracks the dict), then evict over cap
                while self._direct_results_order:
                    head = self._direct_results_order[0]
                    if head not in self._direct_results:
                        self._deferred_seals.pop(head, None)
                        self._direct_results_order.popleft()
                    elif len(self._direct_results) > self._direct_results_cap:
                        ev = self._direct_results_order.popleft()
                        entry = self._direct_results.pop(ev, None)
                        contained = self._deferred_seals.pop(ev, None)
                        if (
                            contained is not None
                            and entry is not None
                            and entry[0] == "val"
                            and TRACKER.count(ev) > 0
                        ):
                            # evicting an owner-held object someone still
                            # references: persist it to the head first
                            uploads.append((ev, entry[1], contained))
                    else:
                        break
                # a live never-consumed entry at the front blocks the lazy
                # sweep: periodically compact the deque against the dict
                if len(self._direct_results_order) > 2 * self._direct_results_cap:
                    self._direct_results_order = deque(
                        x
                        for x in self._direct_results_order
                        if x in self._direct_results
                    )
                aid = self._direct_pending.pop(h, None)
                if aid is not None:
                    chan = self._direct_channels.get(aid)
                    if chan is not None:
                        chan.on_result(h)
                unpin.extend(self._direct_arg_pins.pop(h, ()))
            self._direct_cv.notify_all()
        for h in spills:
            key = self._direct_pending.get(h)
            chan = (
                self._direct_channels.get(key)
                if isinstance(key, str) and key.startswith("lease:")
                else None
            )
            item = chan.take_inflight(h) if chan is not None else None
            if item is not None:
                self._lease_spill(item)
        if register:
            self._flusher.note_registered_live(register)
        for ev, data, contained in uploads:
            if not self._upload_owned(ev, data, contained):
                # we are the ONLY copy: losing the record would strand the
                # ref forever — re-cache (over cap; a later sweep retries)
                with self._direct_cv:
                    if ev not in self._direct_results:
                        self._direct_results[ev] = ("val", data)
                        self._direct_results_order.append(ev)
                    self._deferred_seals.setdefault(ev, contained)
        # release the per-call arg pins (the worker's borrow registrations
        # are on the books before its result reaches us)
        for h in unpin:
            TRACKER.decref(h)
        # one observe per merged delivery batch: the per-item share
        DISPATCH_OVERHEAD_US.observe(
            (time.perf_counter() - t_start) * 1e6 / max(1, len(results)),
            {"stage": "result"},
        )

    def _upload_owned(self, h: str, data: bytes, contained: List[str]) -> bool:
        """Persist an owner-held direct-call result into the head's object
        table (holder = this client) — called when the ref is shared into
        another submission or evicted from the local cache while still
        referenced. After this the normal head-directory lifecycle owns
        the object. Returns False (and logs) if the head stayed
        unreachable through the retry budget — the caller must keep its
        record so a later share can try again."""
        try:
            self.head.call(
                "PutObject",
                {
                    "object_id": h,
                    "data": data,
                    "holder": self.client_id,
                    "contained_ids": sorted(contained),
                },
                retries=8,
                retry_interval=0.25,
            )
            self._flusher.note_registered_live([h])
            return True
        except Exception:  # noqa: BLE001 - head gone; value stays local
            logger.warning("owner-held object upload failed", exc_info=True)
            return False

    def _flush_deferred_seals(self, ids) -> None:
        """Before a submission whose payload references owner-held objects
        leaves this process, upload those objects so any other node can
        resolve them through the head directory."""
        if not self._deferred_seals and not self._direct_pending:
            return
        todo = []
        with self._direct_cv:
            for h in ids:
                contained = self._deferred_seals.pop(h, None)
                if contained is None:
                    if h in self._direct_pending:
                        # result not here yet: flag so the arrival
                        # handler uploads instead of deferring (the
                        # consumer will dep-wait on the head directory)
                        self._shared_pending.add(h)
                    continue
                entry = self._direct_results.get(h)
                if entry is not None and entry[0] == "val":
                    todo.append((h, entry[1], contained))
        for h, data, contained in todo:
            if not self._upload_owned(h, data, contained):
                # keep the record: the dependent submission will dep-wait,
                # and the next share (or eviction) retries the upload.
                # Also restore the VALUE: a concurrent cap-eviction sweep
                # may have dropped it while the marker was popped (the
                # sweep skips its own upload when it sees no marker) —
                # without this the only copy of the object is lost
                with self._direct_cv:
                    self._deferred_seals.setdefault(h, contained)
                    if h not in self._direct_results:
                        self._direct_results[h] = ("val", data)
                        self._direct_results_order.append(h)

    def _fallback_submit(self, item: dict) -> None:
        """Route a direct-call item through the head-scheduled path (actor
        restarting, worker gone, or no direct route)."""
        from ray_tpu.core.refcount import TRACKER

        with self._direct_cv:
            self._direct_pending.pop(item["ref"], None)
            self._shared_pending.discard(item["ref"])
            unpin = self._direct_arg_pins.pop(item["ref"], ())
            self._direct_cv.notify_all()
        self._submit_actor_lease(
            task_id=item["task_id"],
            actor_id=item["actor_id"],
            name=item["name"],
            payload=item["payload"],
            return_id=item["ref"],
            arg_ids=item["arg_ids"],
        )
        # the lease registers us as the return's holder head-side — the
        # local release is owed from now on (zero-safe: the caller may
        # have dropped the ref already)
        self._flusher.note_registered_live([item["ref"]])
        # the lease (queued before this release can flush) pins the args
        # head-side for the task's lifetime
        for h in unpin:
            TRACKER.decref(h)

    def _note_lineage(self, item: dict) -> None:
        """Retain a leased task's submit item as owner-side lineage (the
        reference keeps lineage at the owner, not the GCS): if every copy
        of its return object later dies, `_maybe_resubmit_lost` rebuilds
        it by resubmitting this item through head scheduling. Bounded by
        `owner_lineage_cap_mb` (LRU by submission order)."""
        from ray_tpu.config import cfg

        cap = int(cfg.owner_lineage_cap_mb) << 20
        size = len(item.get("payload") or b"") + len(
            item.get("fn_blob") or b""
        )
        if size > cap:
            return
        with self._lineage_lock:
            old = self._lineage.pop(item["ref"], None)
            if old is not None:
                self._lineage_bytes -= old["_lineage_bytes"]
            item["_lineage_bytes"] = size
            item["_recon_attempts"] = 0
            self._lineage[item["ref"]] = item
            self._lineage_bytes += size
            while self._lineage_bytes > cap and self._lineage:
                _, evicted = self._lineage.popitem(last=False)
                self._lineage_bytes -= evicted["_lineage_bytes"]

    def _maybe_resubmit_lost(self, ref_hex: str, exc: BaseException) -> bool:
        """Owner-side lineage reconstruction: the head sealed this object
        ObjectLostError (typically "no re-executable lineage" — leased
        direct-dispatch tasks never registered a spec head-side). If we
        still hold the task's lineage and its retry budget isn't spent,
        resubmit it through per-task head scheduling — SYNCHRONOUSLY, so
        the head has already cleared the stale error entry when the
        caller's wait loop polls again (no stale-error re-read burning
        attempts). Returns True when the caller should keep waiting.

        `max_retries=0` items never resubmit (at-most-once preserved);
        `OwnerDiedError` is deliberately excluded — OUR owner is us, and
        a foreign owner's death is a fate-sharing verdict, not a loss."""
        from ray_tpu.core.object_store import ObjectLostError

        if not isinstance(exc, ObjectLostError):
            return False
        with self._lineage_lock:
            item = self._lineage.get(ref_hex)
            if item is None:
                return False
            if item["_recon_attempts"] >= int(item.get("_max_retries", 0)):
                return False
            item["_recon_attempts"] += 1
            attempt = item["_recon_attempts"]
        lease = LeaseRequest(
            task_id=item["task_id"],
            name=item["name"],
            payload=item["payload"],
            return_ids=[item["ref"]],
            resources=dict(item["_resources"]),
            kind="task",
            max_retries=item["_max_retries"],
            arg_ids=item["arg_ids"],
            deps=[],
            client_id=self.client_id,
            trace=item.get("trace"),
            fn_blob=item["fn_blob"],
            fn_id=item["fn_id"],
            fn_cache=item["fn_cache"],
            runtime_env=item.get("runtime_env"),
        )
        lease.attempt = attempt  # joint budget with head-side retries
        log = logging.getLogger(__name__)
        try:
            self.head.call(
                "SubmitLease",
                lease,
                timeout=30.0,
                retries=3,
                retry_interval=0.25,
            )
        except Exception:  # noqa: BLE001 - loss stands; caller raises
            with self._lineage_lock:
                if ref_hex in self._lineage:
                    self._lineage[ref_hex]["_recon_attempts"] -= 1
            return False
        self.metrics["lineage_resubmits"] += 1
        log.info(
            "resubmitting lost leased-task object %s through head "
            "scheduling (owner-side lineage, attempt %d/%d)",
            ref_hex[:8],
            attempt,
            item["_max_retries"],
        )
        return True

    def _lease_spill(self, item: dict, may_have_run: bool = False) -> None:
        """Route a leased task back through per-task head scheduling
        (lease loss, stall recall, worker rejection) — the direct-path
        analog of ``_fallback_submit``. Idempotent per ref: a result that
        raced in (or an earlier spill) already cleared the pending entry,
        and re-submitting then would just re-execute for nothing.

        ``may_have_run``: the item was in flight when its lease died, so
        the worker may have (partially) executed it. A task with no
        retry budget then FAILS instead of re-running — the head path's
        worker-death semantics for max_retries=0 (at-most-once held)."""
        from ray_tpu.core.refcount import TRACKER

        with self._direct_cv:
            if item["ref"] not in self._direct_pending:
                return  # already resolved or already spilled
            self._direct_pending.pop(item["ref"], None)
            self._shared_pending.discard(item["ref"])
            unpin = self._direct_arg_pins.pop(item["ref"], ())
            fail = may_have_run and int(item.get("_max_retries", 0)) <= 0
            if fail:
                self._direct_results[item["ref"]] = (
                    "err",
                    pickle.dumps(
                        RuntimeError(
                            f"worker died running {item['name']} "
                            "(max_retries=0: not re-executed)"
                        )
                    ),
                )
                self._direct_results_order.append(item["ref"])
            self._direct_cv.notify_all()
        if fail:
            for h in unpin:
                TRACKER.decref(h)
            return
        lease = LeaseRequest(
            task_id=item["task_id"],
            name=item["name"],
            payload=item["payload"],
            return_ids=[item["ref"]],
            resources=dict(item["_resources"]),
            kind="task",
            max_retries=item["_max_retries"],
            arg_ids=item["arg_ids"],
            deps=[],
            client_id=self.client_id,
            trace=item.get("trace"),
            fn_blob=item["fn_blob"],
            fn_id=item["fn_id"],
            fn_cache=item["fn_cache"],
            runtime_env=item.get("runtime_env"),
        )
        self._sender.enqueue("lease", lease)
        self.metrics["lease_spillbacks"] += 1
        LEASE_SPILLBACKS.inc()
        # the lease registers us as the return's holder head-side — the
        # local release is owed from now on; the queued lease re-pins the
        # args head-side before this unpin can flush
        self._flusher.note_registered_live([item["ref"]])
        for h in unpin:
            TRACKER.decref(h)

    def _direct_note_head_resolved(self, h: str) -> None:
        """A direct-call ref resolved through the head directory while its
        push was still pending: the push was lost (worker-side transient
        RPC failure — the seal reached the head anyway). Drop the pending
        entry and release its arg pins so later gets of this ref go
        straight to the head instead of stalling direct_wait_fallback_s,
        and the entry doesn't leak for the session. Safe: the seal landing
        at the head proves the worker finished with the args."""
        if h not in self._direct_pending:
            return
        from ray_tpu.core.refcount import TRACKER

        with self._direct_cv:
            self._direct_pending.pop(h, None)
            unpin = self._direct_arg_pins.pop(h, ())
            self._direct_cv.notify_all()
        for p in unpin:
            TRACKER.decref(p)

    def _drop_direct_channel(self, actor_id: str, chan) -> None:
        with self._lock:
            if self._direct_channels.get(actor_id) is chan:
                del self._direct_channels[actor_id]

    def _wait_direct(
        self, h: str, deadline: Optional[float]
    ) -> Optional[tuple]:
        """Wait for a direct-call result. Returns the (kind, payload) tuple,
        or None if the ref fell back to the head path (or the push is
        taking long enough that the head directory is the better bet)."""
        # a direct result push can be lost (transient caller-side RPC
        # failure); the seal still reaches the head, so after this long a
        # getter stops trusting the push channel and resolves there
        give_up = time.monotonic() + self._direct_wait_fallback_s
        with self._direct_cv:
            while True:
                if h in self._direct_results:
                    return self._direct_results[h]
                if h not in self._direct_pending:
                    return None
                now = time.monotonic()
                if now >= give_up:
                    return None  # head WaitObject takes over (seal landed)
                wait = min(0.5, give_up - now)
                if deadline is not None:
                    wait = min(wait, deadline - now)
                    if wait <= 0:
                        raise GetTimeoutError(
                            f"get() timed out waiting for {h}"
                        )
                self._direct_cv.wait(timeout=wait)

    def _consume_direct(self, h: str, entry: tuple) -> Tuple[bool, Any]:
        """(resolved, value); raises for error results. Successfully
        consumed entries are dropped — later gets resolve through the head
        directory, which received the same seal."""
        kind, payload = entry
        if kind == "err":
            with self._direct_cv:
                self._direct_results.pop(h, None)
            raise pickle.loads(payload)
        if kind == "val":
            value = self._loads_tracking(payload)
            with self._direct_cv:
                if h not in self._deferred_seals:
                    # owner-held entries stay cached (we are the only
                    # record of the object until share/evict uploads it);
                    # head-sealed entries drop — later gets use the head
                    self._direct_results.pop(h, None)
            return True, value
        # sealed to the actor's node store: fetch from that agent directly
        seal = payload
        with self._lock:
            client = self._agents.get(seal.node_id)
        if client is not None:
            try:
                data = self._socket_fetch(seal.node_id, h)
                if data is None:
                    data = client.call(
                        "FetchObject",
                        {"object_id": h, "purpose": "get"},
                        timeout=120.0,
                    )
                value = self._loads_tracking(data)
                with self._direct_cv:
                    self._direct_results.pop(h, None)
                return True, value
            except (RpcError, KeyError, TimeoutError):
                pass
        return False, None  # fall back to the head-located fetch

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------
    def create_actor(
        self,
        cls: type,
        args: tuple,
        kwargs: dict,
        *,
        resources: Dict[str, float],
        name: Optional[str] = None,
        lifetime: Optional[str] = None,
        max_restarts: int = 0,
        max_concurrency: Optional[int] = None,
        concurrency_groups: Optional[Dict[str, int]] = None,
        scheduling_strategy: Any = None,
        runtime_env: Optional[dict] = None,
        **_ignored,
    ) -> RemoteActorHandle:
        from ray_tpu.core.refcount import collect_serialized

        if lifetime not in (None, "detached", "non_detached"):
            raise ValueError(
                f"lifetime must be 'detached' or 'non_detached', "
                f"got {lifetime!r}"
            )

        _ship_module_by_value(cls)
        actor_id = new_id()
        with collect_serialized() as arg_ids:
            payload = wire.dumps((cls, args, kwargs))
        self._flush_deferred_seals(arg_ids)
        lease = LeaseRequest(
            task_id=new_id(),
            name=f"{cls.__name__}.__init__",
            payload=payload,
            return_ids=[],
            resources=resources,
            kind="actor_creation",
            actor_id=actor_id,
            max_retries=0,
            strategy=scheduling_strategy,
            runtime_env=(
                {**(self.runtime_env or {}), **runtime_env}
                if runtime_env
                else self.runtime_env
            ),
            arg_ids=sorted(arg_ids),
            client_id=self.client_id,
        )
        req = {
            "spec": lease,
            "name": name,
            "class_name": cls.__name__,
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "concurrency_groups": dict(concurrency_groups or {}),
            "lifetime": lifetime,
        }
        if name is None:
            # control-plane fast path: unnamed creations ride the ordered
            # client pipeline (one ClientBatch can carry many creations),
            # so a churn loop never serializes on per-creation replies
            # from a loaded head. The actor id is client-minted, so the
            # handle is valid immediately; WaitActor tolerates the
            # message still being in flight.
            self._sender.enqueue("create_actor", req)
        else:
            # named creation stays synchronous: the name-taken error must
            # surface to this caller, not vanish into the pipeline
            self.head.call("CreateActor", req)
        return RemoteActorHandle(self, actor_id, cls)

    def get_actor(self, name: str) -> RemoteActorHandle:
        info = self._read("GetActor", {"name": name})
        return RemoteActorHandle(self, info.actor_id, object)

    def kill_actor(self, handle: RemoteActorHandle, no_restart: bool = True) -> None:
        # rides the same ordered pipeline as creations so a create→kill
        # pair can never arrive reversed; wait=True keeps the
        # "processed by the head when this returns" semantics, and the
        # bounded wait keeps the pre-pipeline contract that a kill
        # against an unreachable head RAISES instead of hanging forever
        self._sender.enqueue(
            "kill_actor",
            {"actor_id": handle._actor_id, "no_restart": no_restart},
            wait=True,
            wait_timeout=30.0,
        )

    def actor_location(self, actor_id: str):
        """(node_id, agent_address) of an actor, or (None, None) while it
        is pending placement. Used for locality-aware dispatch (e.g. the
        serve proxy pinning shm-streaming calls to same-host replicas)."""
        try:
            info = self._read(
                "WaitActor", {"actor_id": actor_id, "timeout": 0.01}
            )
        except Exception:  # noqa: BLE001
            return None, None
        return info.node_id, info.address

    def wait_actor_alive(self, handle: RemoteActorHandle, timeout: float = 30.0):
        """Event-driven: each round is a server-side long-poll (WaitActor),
        so state changes propagate at RPC latency with no sleep loop."""
        deadline = time.monotonic() + timeout
        while True:
            window = min(5.0, max(0.1, deadline - time.monotonic()))
            try:
                info = self._read(
                    "WaitActor",
                    {"actor_id": handle._actor_id, "timeout": window},
                    timeout=window + 15.0,
                )
            except ValueError:
                # creations ride the pipelined client batch: this poll can
                # legitimately beat the creation message to the head (or
                # span a head restart that hasn't replayed it yet) — keep
                # waiting out OUR deadline before declaring it unknown
                if time.monotonic() >= deadline:
                    raise
                continue
            if info.state == "ALIVE":
                return info
            if info.state == "DEAD":
                raise RuntimeError(f"actor {handle._actor_id} died during creation")
            if time.monotonic() >= deadline:
                raise TimeoutError("actor did not become alive in time")

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------
    def put_object(self, value: Any) -> ObjectRef:
        from ray_tpu.core.refcount import collect_serialized

        ref = ObjectRef.new(owner="driver")
        with collect_serialized() as contained:
            data = wire.dumps(value)
        self._flush_deferred_seals(contained)
        self.head.call(
            "PutObject",
            {
                "object_id": ref.hex,
                "data": data,
                "holder": self.client_id,
                "contained_ids": sorted(contained),
            },
        )
        self._flusher.note_registered([ref.hex])
        return ref

    def _loads_tracking(self, data: bytes) -> Any:
        from ray_tpu.core.refcount import loads_tracking

        return loads_tracking(self._flusher, data)

    def object_locations(self, refs: List[ObjectRef]) -> Dict[str, List[str]]:
        """hex -> node ids currently holding the object (best-effort,
        non-blocking; the head's object directory)."""
        try:
            return self._read(
                "LocateObjects", {"object_ids": [r.hex for r in refs]}
            )
        except Exception:  # noqa: BLE001
            return {}

    def object_sizes(self, refs: List[ObjectRef]) -> Dict[str, int]:
        """hex -> sealed byte size (0 = unknown); head object directory."""
        try:
            return self._read(
                "ObjectSizes", {"object_ids": [r.hex for r in refs]}
            )
        except Exception:  # noqa: BLE001
            return {}

    def get_object(self, ref: ObjectRef, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        h = ref.hex
        if self._push_enabled and (
            h in self._direct_pending or h in self._direct_results
        ):
            entry = self._wait_direct(h, deadline)
            if entry is not None:
                resolved, value = self._consume_direct(h, entry)
                if resolved:
                    return value
        while True:
            # a deferred (owner-held) result can land locally while we're
            # polling a head that will never hear of the object
            if self._push_enabled:
                with self._direct_cv:
                    entry = self._direct_results.get(h)
                if entry is not None:
                    resolved, value = self._consume_direct(h, entry)
                    if resolved:
                        return value
            poll = 2.0
            budget = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
                poll = min(poll, remaining)
                # head-retry loop bounded by the caller's FULL remaining
                # get() budget (+grace for one in-flight reply) — capping
                # at the poll slice would abort a 60s get() 3s into a 5s
                # head restart
                budget = remaining + 1.0
            try:
                reply = self._read(
                    "WaitObject",
                    {"object_id": ref.hex, "timeout": poll},
                    deadline_s=budget,
                )
            except RpcDeadlineError:
                raise GetTimeoutError(
                    f"get() timed out waiting for {ref} (head unreachable)"
                ) from None
            status = reply["status"]
            if status in ("inline", "error", "located"):
                self._direct_note_head_resolved(h)
            if status == "inline":
                return self._loads_tracking(reply["data"])
            if status == "error":
                exc = pickle.loads(reply["error"])
                if self._maybe_resubmit_lost(h, exc):
                    continue  # owner-side lineage rebuild in flight
                raise exc
            if status == "located":
                gone: List[str] = []
                for nid, addr in reply["locations"]:
                    try:
                        # peer-leased socket first (striped scatter-gather
                        # pull, zero per-transfer head RPCs after the one
                        # grant); chunked RPC on any transport miss
                        data = self._socket_fetch(nid, ref.hex)
                        if data is None:
                            data = self._agent(nid, addr).call(
                                "FetchObject",
                                {"object_id": ref.hex, "purpose": "get"},
                                timeout=120.0,
                            )
                    except KeyError:
                        # definite miss: the node answered without the
                        # object (evicted / lost mid-spill / stale row)
                        gone.append(nid)
                        continue
                    except (RpcError, TimeoutError):
                        continue
                    # deserialize OUTSIDE the try: a KeyError raised by
                    # the payload's own unpickling must surface, not
                    # prune a live location and re-execute the task
                    return self._loads_tracking(data)
                if gone:
                    # the head prunes those locations and, if that was the
                    # last copy, rebuilds through lineage — without this a
                    # stale directory row loops the get forever. Epoch-
                    # stamped: a pre-restart client's stale rows must not
                    # prune the rebuilt head's directory
                    try:
                        self.head.call(
                            "ObjectMissing",
                            {"object_id": ref.hex, "node_ids": gone},
                            timeout=10.0,
                            epoch=self._cluster_epoch,
                        )
                    except Exception:  # noqa: BLE001 - next poll retries
                        pass
            if deadline is not None and time.monotonic() >= deadline:
                raise GetTimeoutError(f"get() timed out waiting for {ref}")

    def get_objects(
        self, refs: List[ObjectRef], timeout: Optional[float] = None
    ) -> List[Any]:
        """Batched list-get: one WaitObjectBatch RPC resolves many refs, and
        co-located payloads ride one FetchObjectBatch per node (the
        reference's batched plasma Get, core_worker Get(batch))."""
        deadline = None if timeout is None else time.monotonic() + timeout
        results: Dict[str, tuple] = {}  # hex -> ("val", v) | ("err", exc)
        order = [r.hex for r in refs]
        if self._push_enabled:
            for h in dict.fromkeys(order):
                if h in self._direct_pending or h in self._direct_results:
                    try:
                        entry = self._wait_direct(h, deadline)
                        if entry is not None:
                            ok, value = self._consume_direct(h, entry)
                            if ok:
                                results[h] = ("val", value)
                    except GetTimeoutError:
                        raise
                    except BaseException as exc:  # noqa: BLE001
                        results[h] = ("err", exc)
        while True:
            unresolved = list(dict.fromkeys(h for h in order if h not in results))
            if not unresolved:
                break
            if self._push_enabled:
                # late-arriving owner-held results resolve locally; the
                # head may never hear of those objects
                for h in unresolved:
                    entry = self._direct_results.get(h)
                    if entry is not None:
                        try:
                            ok, value = self._consume_direct(h, entry)
                            if ok:
                                results[h] = ("val", value)
                        except BaseException as exc:  # noqa: BLE001
                            results[h] = ("err", exc)
                unresolved = [h for h in unresolved if h not in results]
                if not unresolved:
                    break
            poll = 2.0
            budget = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
                poll = min(poll, remaining)
                budget = remaining + 1.0
            try:
                replies = self._read(
                    "WaitObjectBatch",
                    {"object_ids": unresolved, "timeout": poll},
                    timeout=poll + 30.0,
                    deadline_s=budget,
                )
            except RpcDeadlineError:
                missing = [h for h in order if h not in results]
                raise GetTimeoutError(
                    f"get() timed out waiting for {len(missing)} objects "
                    "(head unreachable)"
                ) from None
            located: Dict[tuple, List[str]] = {}
            for h, rep in zip(unresolved, replies):
                status = rep["status"]
                if status in ("inline", "error", "located"):
                    self._direct_note_head_resolved(h)
                if status == "inline":
                    results[h] = ("val", self._loads_tracking(rep["data"]))
                elif status == "error":
                    err = pickle.loads(rep["error"])
                    if not self._maybe_resubmit_lost(h, err):
                        results[h] = ("err", err)
                    # else: left unresolved — the next poll parks on the
                    # owner-side lineage rebuild
                elif status == "located":
                    located.setdefault(tuple(rep["locations"][0]), []).append(h)
            for (nid, addr), hs in located.items():
                try:
                    datas = self._agent(nid, addr).call(
                        "FetchObjectBatch",
                        {"object_ids": hs, "purpose": "get"},
                        timeout=120.0,
                    )
                    for h, d in zip(hs, datas):
                        results[h] = ("val", self._loads_tracking(d))
                except (RpcError, KeyError, TimeoutError):
                    # stale location/partial store: per-ref fallback path
                    for h in hs:
                        try:
                            remaining = None
                            if deadline is not None:
                                remaining = max(0.0, deadline - time.monotonic())
                            results[h] = (
                                "val",
                                self.get_object(ObjectRef(h), remaining),
                            )
                        except BaseException as exc:  # noqa: BLE001
                            results[h] = ("err", exc)
            if deadline is not None and time.monotonic() >= deadline:
                missing = [h for h in order if h not in results]
                if missing:
                    raise GetTimeoutError(
                        f"get() timed out waiting for {len(missing)} objects"
                    )
        out = []
        for h in order:
            kind, v = results[h]
            if kind == "err":
                raise v
            out.append(v)
        return out

    def cancel_object(self, ref: ObjectRef, force: bool = False) -> bool:
        h = ref.hex
        if self._lease_mgr is not None:
            key = self._direct_pending.get(h)
            chan = (
                self._direct_channels.get(key)
                if isinstance(key, str) and key.startswith("lease:")
                else None
            )
            cancelled = chan is not None and chan.cancel(h)
            killed = False
            if not cancelled and chan is not None and force:
                # running on the leased worker: force semantics = kill
                # the worker (the head's force path for its own tasks);
                # pre-sealing below makes the death spill skip this ref
                cancelled = killed = chan.kill_running(h)
            if cancelled:
                # sealed locally: the head never knew this task existed
                from ray_tpu.core.refcount import TRACKER

                unpin = ()
                with self._direct_cv:
                    if h in self._direct_pending:
                        self._direct_pending.pop(h, None)
                        self._shared_pending.discard(h)
                        unpin = self._direct_arg_pins.pop(h, ())
                        self._direct_results[h] = (
                            "err",
                            pickle.dumps(RuntimeError("task cancelled")),
                        )
                        self._direct_results_order.append(h)
                        self._direct_cv.notify_all()
                for p in unpin:
                    TRACKER.decref(p)
                if killed:
                    # retire the channel NOW (its worker is dying): the
                    # other unresolved items respill as never-started
                    # instead of racing the death into may-have-run
                    chan.on_killed()
                return True
        reply = self.head.call(
            "CancelLease", {"object_id": h, "force": force}
        )
        return bool(reply.get("cancelled"))

    def free_objects(self, refs: List[ObjectRef]) -> None:
        self.head.call("FreeObjects", {"object_ids": [r.hex for r in refs]})

    def _agent(self, node_id: str, address: str) -> RpcClient:
        with self._lock:
            client = self._agents.get(node_id)
            if client is None or client.address != address:
                client = RpcClient(address)
                self._agents[node_id] = client
            return client

    # ------------------------------------------------------------------
    # cross-node data plane (transport.py): drivers pull big results
    # over the same peer-leased sockets agents use — one GrantPeerLink
    # per (driver, node) pair, then every fetch is head-free
    # ------------------------------------------------------------------
    def _link_cache(self):
        with self._lock:
            if self._peer_links is None:
                from .transport import PeerLinkCache

                self._peer_links = PeerLinkCache(self._grant_peer_link)
                # renew-while-hot + idle reclamation, the driver-side
                # mirror of the agent's _link_maintenance (without it,
                # every driver link expires 'revoked' at the head ~3xTTL
                # in and pooled sockets linger until process exit)
                threading.Thread(
                    target=self._link_maintenance_loop,
                    name="client-peer-links",
                    daemon=True,
                ).start()
            return self._peer_links

    def _link_maintenance_loop(self) -> None:
        from ray_tpu.config import cfg

        while not self._stop_event.wait(
            max(1.0, cfg.peer_link_ttl_s / 2.0)
        ):
            cache = self._peer_links
            if cache is None:
                continue
            try:
                hot = cache.hot_links(cfg.peer_link_ttl_s)
                if hot:
                    self.head.call(
                        "RenewPeerLinks",
                        {"link_ids": hot},
                        timeout=5.0,
                        epoch=self._cluster_epoch,
                    )
                for link in cache.sweep_idle(cfg.peer_link_idle_ttl_s):
                    try:
                        self.head.call(
                            "ReturnPeerLink",
                            {"link_id": link.link_id},
                            timeout=5.0,
                            epoch=self._cluster_epoch,
                        )
                    except Exception:  # noqa: BLE001 - sweep reclaims
                        pass
            except Exception:  # noqa: BLE001 - upkeep never kills the loop
                if self._stop_event.is_set():
                    return

    def _grant_peer_link(self, node_id: str):
        from .transport import PeerLink

        try:
            rep = self.head.call(
                "GrantPeerLink",
                {"src_node": self.client_id, "dst_node": node_id},
                timeout=10.0,
                epoch=self._cluster_epoch,
            )
        except Exception:  # noqa: BLE001 - head busy: RPC path still works
            return None
        if not rep.get("granted"):
            return None
        return PeerLink(
            rep["link_id"],
            node_id,
            rep["endpoint"],
            rep["token"],
            rep.get("epoch"),
            src_node=self.client_id,
        )

    def _socket_fetch(
        self, nid: str, h: str, land: "Optional[str]" = None
    ) -> "Optional[memoryview]":
        """Socket pull of one object from a node's data server. None =
        plane unavailable for this transfer (caller uses the FetchObject
        RPC); KeyError propagates (definite miss — the caller prunes the
        location). Returns a READ-ONLY view: numpy payloads deserialize
        as immutable views exactly like the RPC path's bytes reply.

        ``land`` defaults to None: a generic get must not stage its raw
        RTP5 byte stream in HBM (headers, pickle opcodes, and non-tensor
        payloads would transiently consume device memory equal to the
        whole object). Tensor-heavy consumers opt in by passing
        ``land='device'`` or by fetching under an explicit
        ``device_plane.landing("device")`` scope (rdt pulls, elastic
        ``fetch_sealed``) — landed stripes then stream device-side in
        flight so device frames deserialize against warm pages."""
        from ray_tpu.config import cfg

        if not cfg.native_net:
            return None
        from .device_plane import landing_requested
        from .transport import LinkRejectedError, StripeFetchError
        from .transport import fetch_bytes as _fetch_bytes

        if land is None and landing_requested():
            land = "device"
        link = self._link_cache().get(nid)
        if link is None:
            return None
        try:
            return memoryview(
                _fetch_bytes(link, h, purpose="get", land=land)
            ).toreadonly()
        except KeyError:
            raise
        except LinkRejectedError:
            self._peer_links.drop(nid, link.link_id)
            return None
        except (StripeFetchError, ConnectionError, TimeoutError, OSError):
            return None

    # ------------------------------------------------------------------
    # placement groups
    # ------------------------------------------------------------------
    def create_placement_group(
        self,
        bundles: List[Dict[str, float]],
        strategy: str = "PACK",
        avoid_nodes: Optional[List[str]] = None,
    ) -> str:
        reply = self.head.call(
            "CreatePlacementGroup",
            {
                "bundles": bundles,
                "strategy": strategy,
                "avoid_nodes": list(avoid_nodes or ()),
            },
        )
        return reply["pg_id"]

    # ------------------------------------------------------------------
    # elastic-training gang membership (train/elastic.py)
    # ------------------------------------------------------------------
    def gang_register(
        self,
        gang_id: str,
        members: Dict[int, str],
        min_size: int = 1,
        epoch_floor: int = 0,
        want_world: int = 0,
        resources_per_rank: Optional[Dict[str, float]] = None,
        grow: bool = False,
    ) -> int:
        # re-registration is the designed recovery path (monotone epoch
        # + epoch_floor), so retrying through a head blip/failover is
        # safe — and a zero-retry register right after placement would
        # abort fit() on a transient, leaking the just-placed gang
        reply = self.head.call(
            "GangRegister",
            {
                "gang_id": gang_id,
                "owner": self.client_id,
                "members": {str(r): n for r, n in members.items()},
                "min_size": min_size,
                "epoch_floor": epoch_floor,
                # elasticity plane (PR 19): the driver's grow-back want
                # and per-rank shape feed the unified demand matrix
                "want_world": int(want_world),
                "resources_per_rank": dict(resources_per_rank or {}),
                "grow": bool(grow),
            },
            retries=8,
            retry_interval=0.25,
        )
        return int(reply["epoch"])

    def gang_hint(self, gang_id: str) -> dict:
        """Poll the elasticity controller's sustainable-world verdict
        for one gang (``{"world_hint": int|None, "epoch": int}``)."""
        return self._read("GangHint", {"gang_id": gang_id})

    def gang_sync(
        self, gang_id: str, epoch: int, timeout: float = 0.0
    ) -> dict:
        return self._read(
            "GangSync",
            {"gang_id": gang_id, "epoch": epoch, "timeout": timeout},
            timeout=timeout + 15.0,
        )

    def gang_fence(self, gang_id: str, reason: str = "fence") -> int:
        reply = self.head.call(
            "GangFence", {"gang_id": gang_id, "reason": reason}
        )
        return int(reply["epoch"])

    def gang_unregister(self, gang_id: str) -> None:
        self.head.call("GangUnregister", {"gang_id": gang_id})

    def free_objects(self, hex_ids: List[str]) -> None:
        """Force-free object-plane entries this process knows are dead
        (elastic state generations past their retention window)."""
        if not hex_ids:
            return
        self.head.call("FreeObjects", {"object_ids": list(hex_ids)})

    def wait_placement_group(self, pg_id: str, timeout: float = 30.0) -> List[str]:
        deadline = time.monotonic() + timeout
        while True:
            window = min(5.0, max(0.1, deadline - time.monotonic()))
            reply = self._read(
                "WaitPlacementGroup",
                {"pg_id": pg_id, "timeout": window},
                timeout=window + 15.0,
            )
            if reply["ready"]:
                return reply["node_per_bundle"]
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"placement group {pg_id} not ready in {timeout}s"
                )

    def remove_placement_group(self, pg_id: str) -> None:
        self.head.call("RemovePlacementGroup", {"pg_id": pg_id})

    # ------------------------------------------------------------------
    # kv + introspection
    # ------------------------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        self.head.call("KvPut", {"key": key, "value": value})

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._read("KvGet", {"key": key})

    def kv_del(self, key: str) -> None:
        self.head.call("KvDel", {"key": key})

    def kv_keys(self, prefix: str = "") -> List[str]:
        return self._read("KvKeys", {"prefix": prefix})

    def nodes_info(self) -> List[Dict[str, Any]]:
        return self._read("ClusterInfo")["nodes"]

    def pending_resource_demands(self) -> List[Dict[str, float]]:
        """Autoscaler demand feed (queued/infeasible leases + PG bundles)."""
        return self._read("PendingDemands")

    def cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.nodes_info():
            if not n["Alive"]:
                continue
            for k, v in n["Resources"].items():
                out[k] = out.get(k, 0.0) + v
        return out

    def available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.nodes_info():
            if not n["Alive"]:
                continue
            for k, v in n["Available"].items():
                out[k] = out.get(k, 0.0) + v
        return out

    def query_state(self, kind: str = "summary") -> Any:
        return self._read("QueryState", {"kind": kind})

    def timeline(self, filename: Optional[str] = None) -> List[dict]:
        """Chrome-trace of head-observed lease lifecycle events."""
        spans = self._read("Timeline", timeout=60.0)
        if filename:
            import json

            with open(filename, "w") as f:
                json.dump(spans, f)
        return spans

    def shutdown(self) -> None:
        from ray_tpu.core import refcount

        # idempotent: the atexit hook, __exit__, and explicit shutdown()
        # may all fire; only the first runs the teardown
        if self._shutdown_done:
            return
        self._shutdown_done = True
        self._stop_event.set()
        import atexit

        try:
            atexit.unregister(self._atexit_hook)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
        if self._lease_mgr is not None:
            self._lease_mgr.stop()  # no new grants/channels from here on
        for chan in list(self._direct_channels.values()):
            chan.stop()  # lease channels also enqueue their lease_return
        self._direct_channels.clear()
        self._hotloop.stop()
        self._result_sink.close()
        if self._callback_server is not None:
            self._callback_server.stop()
            self._callback_server = None
        if self._owns_flusher:
            # release every id this driver still counts so the cluster can
            # free driver-owned objects (job-exit cleanup analog). BOUNDED:
            # a wedged pipeline must not hang process exit — undelivered
            # releases are covered by the head's disconnect reap dropping
            # this client's holder rows
            self._ref_wait_timeout = 10.0
            self._flusher.stop(release_all=True)
            refcount.clear_consumer(self._flusher)
        self._sender.stop()
        try:
            # clean driver exit: the head reaps this client's non-detached
            # actors (detached ones survive — reference job-exit
            # semantics). Best-effort: a crashed driver skips this and
            # its actors linger until killed explicitly.
            self.head.call(
                "DisconnectClient", {"client_id": self.client_id}, timeout=5.0
            )
        except Exception:  # noqa: BLE001 - best-effort: call() re-raises
            pass  # server-side exceptions verbatim (not just RpcError)
        self._pipe_chan.close()
        self.head.close()
        with self._lock:
            for client in self._agents.values():
                client.close()
            self._agents.clear()
            if self._peer_links is not None:
                self._peer_links.close()
                self._peer_links = None


def connect(address: str, runtime_env: Optional[dict] = None) -> RemoteRuntime:
    return RemoteRuntime(address, runtime_env=runtime_env)
