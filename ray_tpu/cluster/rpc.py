"""gRPC plumbing for the distributed runtime.

The reference runs every control-plane boundary over gRPC with protoc-generated
services (/root/reference/src/ray/rpc/grpc_server.h, src/ray/protobuf/*.proto).
We keep gRPC as the wire (HTTP/2 framing, flow control, connection reuse) but
register *generic* unary handlers dispatched by method name with pickled
payloads — the framework's control messages are Python dataclasses, and a
dynamic schema keeps the RPC layer to one file instead of 36 .proto files.
Messages ride the pickle-5 out-of-band frame format (serialization.py):
numpy buffers inside any request/reply travel as raw frame segments and
deserialize as zero-copy views over the received message.

Every handler runs server-side in a thread pool; exceptions are pickled and
re-raised at the caller (the RetryableGrpcClient contract,
src/ray/rpc/retryable_grpc_client.h — retries here are explicit via
``RpcClient.call(retries=)``).
"""
from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import grpc

from . import serialization as wire

_MAX_MSG = 256 * 1024 * 1024


class RpcError(Exception):
    """Transport-level failure (peer dead/unreachable)."""


class PeerUnavailableError(RpcError):
    """The peer's circuit breaker is open: calls fail fast without
    touching the wire until a half-open probe succeeds."""


class RpcDeadlineError(RpcError):
    """The caller's overall deadline was exhausted across retries."""


class RpcStaleEpochError(Exception):
    """The caller stamped this RPC with a cluster epoch older than the
    receiver's: the sender joined a PREVIOUS head incarnation and its
    state (lease table rows, object locations, actor attachments) may
    have been rebuilt since. NOT an RpcError — handler-level exceptions
    re-raise at the caller immediately without consuming the retry
    budget, so stale traffic can never mutate the rebuilt tables by
    retrying its way in. The sender re-registers to adopt the new epoch
    (re-registration is the resync protocol) and only then resumes."""


class RpcNotLeaderError(RpcError):
    """The receiving head is not the cluster leader (a warm standby, or
    a deposed leader that fenced itself after observing a higher cluster
    epoch). Handler-level: re-raised at the caller immediately, never
    consuming the transport retry budget. Subclasses RpcError on
    purpose — the dozens of pre-existing ``except RpcError`` resilience
    paths (requeue, retry-later, spill) are exactly the right degraded
    behavior during a fenced window, while failover-aware callers catch
    this type FIRST and walk ``leader_hint`` / their head-candidate
    list to the real leader."""

    def __init__(self, msg: str, leader_hint: str = ""):
        super().__init__(msg)
        self.leader_hint = leader_hint

    def __reduce__(self):
        return (RpcNotLeaderError, (self.args[0], self.leader_hint))


class RpcUnknownMethodError(RpcError):
    """The peer has no handler registered for the requested method —
    dispatch-table drift (a caller invoking a kind the receiving side
    never registered), not a transport failure. Raised to the caller
    immediately, WITHOUT consuming the retry budget: gRPC's raw
    UNIMPLEMENTED used to read as a dead peer and burn every retry on a
    method that can never exist."""


class _Blackholed(Exception):
    """Injected partition: the peer is unreachable from this process.
    Handled exactly like a transport failure (retries, breaker)."""


class FencedPayload:
    """Wire envelope stamping a request with the sender's cluster epoch
    (``RpcClient.call(epoch=...)``). A server whose ``epoch`` is set (the
    head) rejects envelopes from an older epoch with
    :class:`RpcStaleEpochError` BEFORE the handler runs — stale traffic
    can never mutate rebuilt tables. Servers with no epoch (agents,
    workers) and methods in ``fence_exempt`` just unwrap."""

    __slots__ = ("epoch", "payload")

    def __init__(self, epoch: int, payload: Any):
        self.epoch = epoch
        self.payload = payload

    def __reduce__(self):
        return (FencedPayload, (self.epoch, self.payload))


class FaultInjection:
    """Runtime-mutable, process-local fault injection for chaos runs.

    The env-driven ``RAY_TPU_RPC_CHAOS`` knob (``_Chaos`` below) covers
    probabilistic per-method faults fixed at process start; this registry
    is the orchestrator-facing surface — per-PEER blackholes (partition)
    and delays (straggler ramps) that can be toggled mid-run. Injection
    happens inside ``RpcClient.call`` so the blackholed traffic exercises
    the real retry/breaker/recovery machinery."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._blackholed: set = set()
        self._delays: Dict[str, float] = {}

    def blackhole(self, address: str) -> None:
        with self._lock:
            self._blackholed.add(address)

    def heal(self, address: str) -> None:
        with self._lock:
            self._blackholed.discard(address)
            self._delays.pop(address, None)

    def set_delay(self, address: str, seconds: float) -> None:
        with self._lock:
            if seconds <= 0:
                self._delays.pop(address, None)
            else:
                self._delays[address] = float(seconds)

    def clear(self) -> None:
        with self._lock:
            self._blackholed.clear()
            self._delays.clear()

    def check(self, address: str) -> float:
        """Returns the injected delay for ``address`` (0 if none); raises
        ``_Blackholed`` if the peer is partitioned away."""
        with self._lock:
            if address in self._blackholed:
                raise _Blackholed(f"chaos: peer {address} blackholed")
            return self._delays.get(address, 0.0)


FAULTS = FaultInjection()


class CircuitBreaker:
    """Per-peer circuit breaker (RetryableGrpcClient's
    server-unavailable-timeout analog, src/ray/rpc/retryable_grpc_client.h).

    Closed → transport failures spanning ``rpc_breaker_window_s`` with no
    intervening success → Open (calls fail fast, node-unreachable
    callbacks fire) → after ``rpc_breaker_cooldown_s`` one half-open
    probe is allowed; its success closes the circuit, its failure
    re-opens it. State is shared per peer address across every RpcClient
    in the process, so a wedged transport fails fast everywhere instead
    of stalling each caller for its full timeout."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, address: str):
        self.address = address
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self._first_failure: Optional[float] = None
        self._last_failure = 0.0
        self._fail_count = 0
        self._open_until = 0.0
        self._probe_in_flight = False
        self.open_count = 0
        # id(owner) -> callback; fired (outside the lock) on each
        # closed->open transition. Owners unregister via remove_callback.
        self._callbacks: Dict[int, Callable[[], None]] = {}

    def add_callback(self, owner: Any, fn: Callable[[], None]) -> None:
        with self._lock:
            self._callbacks[id(owner)] = fn

    def remove_callback(self, owner: Any) -> None:
        with self._lock:
            self._callbacks.pop(id(owner), None)

    def allow(self) -> bool:
        """May an attempt touch the wire right now?"""
        now = time.monotonic()
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN and now >= self._open_until:
                self.state = self.HALF_OPEN
                self._probe_in_flight = True
                return True
            if self.state == self.HALF_OPEN and not self._probe_in_flight:
                self._probe_in_flight = True
                return True
            return False

    def on_success(self) -> None:
        with self._lock:
            was_open = self.state != self.CLOSED
            self.state = self.CLOSED
            self._first_failure = None
            self._fail_count = 0
            self._probe_in_flight = False
        if was_open:
            BREAKER_STATE.set(0, labels={"peer": self.address})

    def abort_probe(self) -> None:
        """A half-open probe attempt died without a transport verdict
        (e.g. serialization error): release the probe slot so the
        breaker can't wedge in HALF_OPEN forever."""
        with self._lock:
            if self.state == self.HALF_OPEN:
                self._probe_in_flight = False

    def on_failure(self) -> None:
        from ray_tpu.config import cfg

        now = time.monotonic()
        opened = False
        fire: List[Callable[[], None]] = []
        with self._lock:
            if self.state == self.HALF_OPEN:
                # probe failed: straight back to open — and RE-fire the
                # callbacks. A persistently partitioned node can
                # re-register between cooldowns (its own reports still
                # flow); without re-firing, it would stay 'alive' forever
                # while every dispatch to it fails fast.
                self.state = self.OPEN
                self._probe_in_flight = False
                self._open_until = now + cfg.rpc_breaker_cooldown_s
                self.open_count += 1
                opened = True
                fire = list(self._callbacks.values())
            elif self.state == self.OPEN:
                return
            else:
                window = cfg.rpc_breaker_window_s
                # SLIDING window: a failure separated from the previous
                # one by more than the window starts a fresh streak —
                # sparse unrelated timeouts hours apart on a quiet peer
                # must never accumulate into a false open
                if (
                    self._first_failure is None
                    or now - self._last_failure > window
                ):
                    self._first_failure = now
                    self._last_failure = now
                    self._fail_count = 1
                    return
                self._last_failure = now
                self._fail_count += 1
                # open only when a CONTINUOUS failure streak both spans
                # the window and numbers at least the minimum
                if (
                    now - self._first_failure >= window
                    and self._fail_count >= cfg.rpc_breaker_min_failures
                ):
                    self.state = self.OPEN
                    self._open_until = now + cfg.rpc_breaker_cooldown_s
                    self.open_count += 1
                    opened = True
                    fire = list(self._callbacks.values())
        if opened:
            BREAKER_OPENS.inc(labels={"peer": self.address})
            BREAKER_STATE.set(1, labels={"peer": self.address})
            for fn in fire:
                try:
                    fn()
                except Exception:  # noqa: BLE001 - health path best-effort
                    import logging

                    logging.getLogger("ray_tpu.cluster.rpc").exception(
                        "node-unreachable callback failed for %s",
                        self.address,
                    )


_BREAKERS: Dict[str, CircuitBreaker] = {}
# clients per address: breakers for ephemeral peers (worker processes get
# a fresh port per spawn — thousands over an agent's life under actor
# churn) are evicted when their last client closes, instead of growing
# the registry forever
_BREAKER_REFS: Dict[str, int] = {}
_BREAKERS_LOCK = threading.Lock()


def get_breaker(address: str) -> CircuitBreaker:
    with _BREAKERS_LOCK:
        br = _BREAKERS.get(address)
        if br is None:
            br = _BREAKERS[address] = CircuitBreaker(address)
        _BREAKER_REFS[address] = _BREAKER_REFS.get(address, 0) + 1
        return br


def release_breaker(address: str) -> None:
    """Drop one client's hold on ``address``'s breaker; the registry entry
    is evicted with the last hold (its trip counters have already been
    exported through the BREAKER_* metrics)."""
    with _BREAKERS_LOCK:
        n = _BREAKER_REFS.get(address, 0) - 1
        if n <= 0:
            _BREAKER_REFS.pop(address, None)
            _BREAKERS.pop(address, None)
        else:
            _BREAKER_REFS[address] = n


def reset_breakers() -> None:
    """Reset all breaker STATE (tests / chaos teardown) in place: live
    clients hold direct references to their breakers, and stale imports
    of _BREAKERS must keep seeing the shared registry object. Entries
    still referenced by open clients stay registered (with their
    refcounts) — dropping them would split per-peer breaker state the
    moment a new client re-registered the address; only ref-less
    entries are evicted."""
    with _BREAKERS_LOCK:
        for addr, br in list(_BREAKERS.items()):
            with br._lock:
                br.state = br.CLOSED
                br._first_failure = None
                br._fail_count = 0
                br._probe_in_flight = False
            if _BREAKER_REFS.get(addr, 0) <= 0:
                _BREAKERS.pop(addr, None)
                _BREAKER_REFS.pop(addr, None)
_OPTIONS = [
    ("grpc.max_send_message_length", _MAX_MSG),
    ("grpc.max_receive_message_length", _MAX_MSG),
    ("grpc.so_reuseport", 0),
]


from ray_tpu.util.metrics import Counter as _Counter
from ray_tpu.util.metrics import Gauge as _Gauge

RPC_RETRIES = _Counter(
    "rpc_client_retries_total",
    "RPC attempts retried after a transport-level failure.",
    label_names=("method",),
)
RPC_DEADLINE_EXCEEDED = _Counter(
    "rpc_client_deadline_exceeded_total",
    "RPC calls abandoned because the caller's overall deadline expired.",
    label_names=("method",),
)
BREAKER_OPENS = _Counter(
    "rpc_breaker_opens_total",
    "Circuit-breaker closed->open transitions per peer.",
    label_names=("peer",),
)
BREAKER_STATE = _Gauge(
    "rpc_breaker_open",
    "1 while the peer's circuit is open, 0 otherwise.",
    label_names=("peer",),
)


class _ChaosDrop(Exception):
    """Injected message drop — handled exactly like a transport failure
    (same retry budget), so chaos exercises the real recovery path."""


class _Chaos:
    """Message-level failure injection (rpc_chaos.h:24-41 analog).

    Configured by the RAY_TPU_RPC_CHAOS knob, e.g.
    ``ExecuteLeaseBatch:drop=0.1;PushTaskBatch:delay_ms=20`` — each listed
    method gets an independent drop probability (the call raises RpcError
    without ever reaching the peer — the retry/requeue machinery must
    recover) and/or an added delay. Parsed once per process."""

    def __init__(self) -> None:
        import random

        from ray_tpu.config import cfg

        self.rules: Dict[str, Dict[str, float]] = {}
        self._rng = random.Random(0xC4A05)
        spec = cfg.rpc_chaos
        for part in spec.split(";"):
            part = part.strip()
            if not part or ":" not in part:
                continue
            method, params = part.split(":", 1)
            rule: Dict[str, float] = {}
            for kv in params.split(","):
                if "=" in kv:
                    k, v = kv.split("=", 1)
                    try:
                        rule[k.strip()] = float(v)
                    except ValueError:
                        pass
            if rule:
                self.rules[method.strip()] = rule

    def apply(self, method: str) -> None:
        rule = self.rules.get(method)
        if rule is None:
            return
        delay = rule.get("delay_ms", 0.0)
        if delay > 0:
            time.sleep(delay / 1e3)
        if self._rng.random() < rule.get("drop", 0.0):
            raise _ChaosDrop(f"chaos: dropped {method} before send")


_chaos: Optional[_Chaos] = None


def _get_chaos() -> _Chaos:
    global _chaos
    if _chaos is None:
        _chaos = _Chaos()
    return _chaos


class HandlerStats:
    """Per-handler timing (the reference's event-loop/handler stats,
    src/ray/common/asio/instrumented_io_context.h — every posted handler
    is counted and timed). One instance per process; servers share it."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._stats: Dict[str, list] = {}  # name -> [count, total_s, max_s]

    def record(self, name: str, elapsed: float) -> None:
        with self._lock:
            row = self._stats.get(name)
            if row is None:
                row = self._stats[name] = [0, 0.0, 0.0]
            row[0] += 1
            row[1] += elapsed
            if elapsed > row[2]:
                row[2] = elapsed

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {
                name: {
                    "count": c,
                    "total_ms": round(t * 1e3, 3),
                    "mean_ms": round(t / c * 1e3, 3) if c else 0.0,
                    "max_ms": round(mx * 1e3, 3),
                }
                for name, (c, t, mx) in sorted(self._stats.items())
            }


HANDLER_STATS = HandlerStats()


class _GenericHandler(grpc.GenericRpcHandler):
    def __init__(
        self,
        handlers: Dict[str, Callable[[Any], Any]],
        server: "Optional[RpcServer]" = None,
    ):
        self._handlers = handlers
        self._rpc_server = server

    def _unfence(self, name: str, req: Any) -> Any:
        """Enforce epoch fencing on a stamped request. The epoch check is
        strictly-less-than: a sender from THIS incarnation passes; only
        provably-stale traffic — a peer that registered with a PREVIOUS
        head — is rejected, before its handler can touch any table. A
        stamp HIGHER than this server's epoch proves a newer head
        incarnation exists (the sender registered with it): an
        epoch-checking server self-fences via ``on_newer_epoch`` and
        redirects the sender — the deposed-leader half of split-brain
        prevention."""
        if not isinstance(req, FencedPayload):
            return req
        srv = self._rpc_server
        if (
            srv is not None
            and srv.epoch is not None
            and name not in srv.fence_exempt
        ):
            if req.epoch < srv.epoch:
                raise RpcStaleEpochError(
                    f"rpc {name} stamped with epoch {req.epoch} but the "
                    f"cluster epoch is {srv.epoch}; re-register to resync"
                )
            if req.epoch > srv.epoch and srv.on_newer_epoch is not None:
                try:
                    srv.on_newer_epoch(int(req.epoch))
                except Exception:  # noqa: BLE001 - fencing is best-effort here
                    pass
                raise RpcNotLeaderError(
                    f"rpc {name} stamped with epoch {req.epoch} > this "
                    f"head's {srv.epoch}: a newer head incarnation "
                    "exists; this one has fenced itself",
                    leader_hint=srv.not_leader_hint or "",
                )
        return req.payload

    def _refuse_if_not_leader(self, name: str) -> None:
        srv = self._rpc_server
        if (
            srv is not None
            and srv.refuse_non_leader
            and name not in srv.always_serve
        ):
            raise RpcNotLeaderError(
                f"rpc {name}: this head is not the cluster leader "
                f"(role={srv.role_hint})",
                leader_hint=srv.not_leader_hint or "",
            )

    def service(self, handler_call_details):
        name = handler_call_details.method.rsplit("/", 1)[-1]
        fn = self._handlers.get(name)
        if fn is None:
            # unknown method: reply with a typed handler-level error so the
            # caller fails fast with the method name instead of retrying a
            # raw UNIMPLEMENTED as if the peer were down
            def unknown(request_bytes, context, _name=name):
                return cloudpickle.dumps(
                    (
                        False,
                        RpcUnknownMethodError(
                            f"no handler registered for rpc method {_name!r}"
                        ),
                    )
                )

            return grpc.unary_unary_rpc_method_handler(
                unknown,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            )

        def unary(request_bytes, context):
            t0 = time.perf_counter()
            try:
                self._refuse_if_not_leader(name)
                req = self._unfence(name, wire.loads(request_bytes))
                return wire.dumps((True, fn(req)))
            except BaseException as exc:  # noqa: BLE001 - shipped to caller
                try:
                    return cloudpickle.dumps((False, exc))
                except Exception:  # unpicklable exception
                    return cloudpickle.dumps((False, RuntimeError(repr(exc))))
            finally:
                HANDLER_STATS.record(name, time.perf_counter() - t0)

        return grpc.unary_unary_rpc_method_handler(
            unary,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )


class RpcServer:
    """One gRPC server hosting named unary handlers.

    ``handlers`` maps method name -> fn(request_obj) -> response_obj.
    """

    def __init__(
        self,
        handlers: Dict[str, Callable[[Any], Any]],
        host: str = "127.0.0.1",
        port: int = 0,
        max_workers: int = 32,
    ):
        # epoch fencing (set by the head after recovery): stamped requests
        # older than this are rejected with RpcStaleEpochError; methods in
        # fence_exempt (the resync protocol itself) always pass
        self.epoch: Optional[int] = None
        self.fence_exempt: set = set()
        # leadership fencing (replicated control plane): a fenced or
        # standby head sets refuse_non_leader and every method outside
        # always_serve (role probe + observability) raises
        # RpcNotLeaderError with the leader hint BEFORE its handler runs.
        # on_newer_epoch fires when a request stamped with a HIGHER epoch
        # arrives — proof a newer incarnation exists; the head routes it
        # into its step-down path.
        self.refuse_non_leader = False
        self.always_serve: set = {"Ping", "HeadRole", "QueryState"}
        self.not_leader_hint: Optional[str] = None
        self.role_hint = "leader"
        self.on_newer_epoch: Optional[Callable[[int], None]] = None
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_OPTIONS,
        )
        self._server.add_generic_rpc_handlers(
            (_GenericHandler(handlers, server=self),)
        )
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise RpcError(f"could not bind RPC server on {host}:{port}")
        self.address = f"{host}:{self.port}"
        self._server.start()

    def stop(self, grace: float = 0.2) -> None:
        self._server.stop(grace)


class RpcClient:
    """Channel to one peer; ``call(method, payload)`` round-trips an object.

    The full RetryableGrpcClient analog (retryable_grpc_client.h):
    exponential backoff with decorrelated jitter under a cap, caller
    deadline propagation (``deadline_s`` bounds the WHOLE retry loop —
    attempts, injected delays, and backoff sleeps included), and a
    per-peer circuit breaker shared across every client to the same
    address. ``on_unreachable`` registers a callback fired when the
    breaker opens (the head routes it into its health path so a wedged
    transport is declared dead in seconds, not after every caller's
    timeout stacks up)."""

    def __init__(
        self,
        address: str,
        on_unreachable: Optional[Callable[[], None]] = None,
    ):
        self.address = address
        self._channel = grpc.insecure_channel(address, options=_OPTIONS)
        self._methods: Dict[str, Any] = {}
        self._closed = False
        self._breaker = get_breaker(address)
        if on_unreachable is not None:
            self._breaker.add_callback(self, on_unreachable)

    def _method(self, name: str):
        m = self._methods.get(name)
        if m is None:
            m = self._channel.unary_unary(
                f"/rtpu/{name}",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            self._methods[name] = m
        return m

    def call(
        self,
        method: str,
        payload: Any = None,
        timeout: Optional[float] = 30.0,
        retries: int = 0,
        retry_interval: float = 0.1,
        deadline_s: Optional[float] = None,
        epoch: Optional[int] = None,
    ) -> Any:
        """Round-trip ``payload`` to handler ``method``.

        ``timeout`` is the per-attempt RPC deadline; ``deadline_s`` is the
        caller's OVERALL budget — no retry sequence (attempts + backoff)
        ever exceeds it, and per-attempt timeouts shrink to the remaining
        budget. Transport failures (gRPC errors, injected drops/partitions)
        consume the retry budget; handler exceptions re-raise immediately.
        ``epoch`` stamps the request with the sender's cluster epoch
        (epoch-fenced control plane): an epoch-checking receiver rejects
        stale stamps with a non-retryable RpcStaleEpochError."""
        import random

        from ray_tpu.config import cfg

        if epoch is not None:
            payload = FencedPayload(int(epoch), payload)
        data = wire.dumps(payload)
        attempt = 0
        deadline = (
            None if deadline_s is None else time.monotonic() + deadline_s
        )
        # exponential backoff with decorrelated jitter: each sleep draws
        # uniform in [base, 3*prev], capped — retry bursts from many
        # callers desynchronize instead of hammering a recovering peer in
        # lockstep (the previous linear `interval * attempt` ramp kept
        # every waiter phase-aligned).
        backoff = retry_interval
        cap = max(retry_interval, cfg.rpc_backoff_cap_s)
        br = self._breaker

        def _out_of_time() -> bool:
            return deadline is not None and time.monotonic() >= deadline

        def _raise_deadline(cause: Optional[BaseException]) -> None:
            RPC_DEADLINE_EXCEEDED.inc(labels={"method": method})
            raise RpcDeadlineError(
                f"rpc {method} to {self.address} exceeded the caller "
                f"deadline of {deadline_s}s after {attempt + 1} attempt(s)"
            ) from cause

        while True:
            if _out_of_time():
                _raise_deadline(None)
            if not br.allow():
                # circuit open: fail fast without touching the wire. With
                # retries left we keep (bounded) patience — backoff sleeps
                # line the caller up with the half-open probe window.
                if attempt >= retries:
                    raise PeerUnavailableError(
                        f"rpc {method} to {self.address}: circuit open "
                        f"(peer unavailable)"
                    )
                attempt += 1
            else:
                try:
                    delay = FAULTS.check(self.address)
                    if delay > 0:
                        if deadline is not None:
                            delay = min(
                                delay, max(0.0, deadline - time.monotonic())
                            )
                        time.sleep(delay)
                    _get_chaos().apply(method)
                    att_timeout = timeout
                    if deadline is not None:
                        remaining = max(0.001, deadline - time.monotonic())
                        att_timeout = (
                            remaining
                            if timeout is None
                            else min(timeout, remaining)
                        )
                    try:
                        raw = self._method(method)(
                            data, timeout=att_timeout
                        )
                    except ValueError as exc:
                        # grpc raises bare ValueError ("Cannot invoke RPC
                        # on closed channel") when close() raced this
                        # call — a transport failure, not a caller bug:
                        # surface it as RpcError so retry loops that
                        # rebind their channel (head failover) recover
                        # instead of dying on an uncaught ValueError
                        raise RpcError(
                            f"rpc {method} to {self.address}: channel "
                            "closed under the call"
                        ) from exc
                    ok, value = wire.loads(raw)
                    br.on_success()
                    if not ok:
                        raise value
                    return value
                except (grpc.RpcError, _ChaosDrop, _Blackholed) as exc:
                    br.on_failure()
                    if attempt >= retries:
                        raise RpcError(
                            f"rpc {method} to {self.address} failed: "
                            f"{exc.code() if hasattr(exc, 'code') else exc}"
                        ) from exc
                    if _out_of_time():
                        _raise_deadline(exc)
                    attempt += 1
                    RPC_RETRIES.inc(labels={"method": method})
                except BaseException:
                    # no transport verdict (serialization error, interrupt):
                    # release a half-open probe slot instead of wedging the
                    # breaker, and surface the error unchanged
                    br.abort_probe()
                    raise
            backoff = min(
                cap,
                random.uniform(
                    retry_interval, max(retry_interval, 3.0 * backoff)
                ),
            )
            if deadline is not None:
                backoff = min(backoff, max(0.0, deadline - time.monotonic()))
            time.sleep(backoff)

    def close(self) -> None:
        if self._closed:  # idempotent: the breaker hold releases once
            return
        self._closed = True
        self._breaker.remove_callback(self)
        self._channel.close()
        release_breaker(self.address)


def head_candidates(primary: str, extra: str = "") -> List[str]:
    """The ordered head-address candidate list a peer walks when its
    head stops answering as leader: the configured primary, then every
    ``RAY_TPU_HEAD_STANDBYS`` entry (comma-separated). ``primary`` may
    itself be a comma list (clients accept one)."""
    from ray_tpu.config import cfg

    out: List[str] = []
    for part in (primary or "").split(","):
        part = part.strip()
        if part and part not in out:
            out.append(part)
    for part in (extra or cfg.head_standbys or "").split(","):
        part = part.strip()
        if part and part not in out:
            out.append(part)
    return out


def resolve_leader(
    current_address: str, hint: str = "", extra: str = ""
) -> Optional[str]:
    """The ONE candidate-walk both agents and clients use on a
    NotLeader/unreachable head: leadership hint first, then the
    configured address(es) + RAY_TPU_HEAD_STANDBYS. Returns the
    leader's address (possibly ``current_address`` itself), or None
    while nobody leads (mid-failover — callers retry on their own
    cadence)."""
    cands = ([hint] if hint else []) + head_candidates(
        current_address, extra
    )
    found = probe_leader(cands, timeout=2.0)
    return found[0] if found is not None else None


def probe_leader(
    addresses, timeout: float = 2.0
) -> Optional[tuple]:
    """Walk head candidates asking ``HeadRole`` (fence-exempt on every
    head role) and return ``(address, info)`` of the first one answering
    as leader; standby/fenced replies contribute their ``leader_hint``
    as one extra hop. None when nobody is leading yet (mid-failover —
    callers retry on their own cadence)."""
    hints: List[str] = []
    seen: set = set()
    queue = list(addresses)
    while queue:
        addr = queue.pop(0)
        if not addr or addr in seen:
            continue
        seen.add(addr)
        client = RpcClient(addr)
        try:
            info = client.call("HeadRole", {}, timeout=timeout)
        except Exception:  # noqa: BLE001 - dead candidate, keep walking
            continue
        finally:
            client.close()
        if not isinstance(info, dict):
            continue
        if info.get("role") == "leader":
            return addr, info
        hint = info.get("leader_hint")
        if hint and hint not in seen:
            hints.append(hint)
        if not queue and hints:
            queue.extend(hints)
            hints = []
    return None
