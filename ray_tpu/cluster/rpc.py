"""gRPC plumbing for the distributed runtime.

The reference runs every control-plane boundary over gRPC with protoc-generated
services (/root/reference/src/ray/rpc/grpc_server.h, src/ray/protobuf/*.proto).
We keep gRPC as the wire (HTTP/2 framing, flow control, connection reuse) but
register *generic* unary handlers dispatched by method name with cloudpickle
payloads — the framework's control messages are Python dataclasses, and a
dynamic schema keeps the RPC layer to one file instead of 36 .proto files.

Every handler runs server-side in a thread pool; exceptions are pickled and
re-raised at the caller (the RetryableGrpcClient contract,
src/ray/rpc/retryable_grpc_client.h — retries here are explicit via
``RpcClient.call(retries=)``).
"""
from __future__ import annotations

import pickle
import time
from concurrent import futures
from typing import Any, Callable, Dict, Optional

import cloudpickle
import grpc

_MAX_MSG = 256 * 1024 * 1024
# ceiling on any single retry backoff sleep
_BACKOFF_CAP_S = 2.0
_OPTIONS = [
    ("grpc.max_send_message_length", _MAX_MSG),
    ("grpc.max_receive_message_length", _MAX_MSG),
    ("grpc.so_reuseport", 0),
]


class RpcError(Exception):
    """Transport-level failure (peer dead/unreachable)."""


class _ChaosDrop(Exception):
    """Injected message drop — handled exactly like a transport failure
    (same retry budget), so chaos exercises the real recovery path."""


class _Chaos:
    """Message-level failure injection (rpc_chaos.h:24-41 analog).

    Configured by the RAY_TPU_RPC_CHAOS knob, e.g.
    ``ExecuteLeaseBatch:drop=0.1;PushTaskBatch:delay_ms=20`` — each listed
    method gets an independent drop probability (the call raises RpcError
    without ever reaching the peer — the retry/requeue machinery must
    recover) and/or an added delay. Parsed once per process."""

    def __init__(self) -> None:
        import random

        from ray_tpu.config import cfg

        self.rules: Dict[str, Dict[str, float]] = {}
        self._rng = random.Random(0xC4A05)
        spec = cfg.rpc_chaos
        for part in spec.split(";"):
            part = part.strip()
            if not part or ":" not in part:
                continue
            method, params = part.split(":", 1)
            rule: Dict[str, float] = {}
            for kv in params.split(","):
                if "=" in kv:
                    k, v = kv.split("=", 1)
                    try:
                        rule[k.strip()] = float(v)
                    except ValueError:
                        pass
            if rule:
                self.rules[method.strip()] = rule

    def apply(self, method: str) -> None:
        rule = self.rules.get(method)
        if rule is None:
            return
        delay = rule.get("delay_ms", 0.0)
        if delay > 0:
            time.sleep(delay / 1e3)
        if self._rng.random() < rule.get("drop", 0.0):
            raise _ChaosDrop(f"chaos: dropped {method} before send")


_chaos: Optional[_Chaos] = None


def _get_chaos() -> _Chaos:
    global _chaos
    if _chaos is None:
        _chaos = _Chaos()
    return _chaos


class HandlerStats:
    """Per-handler timing (the reference's event-loop/handler stats,
    src/ray/common/asio/instrumented_io_context.h — every posted handler
    is counted and timed). One instance per process; servers share it."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._stats: Dict[str, list] = {}  # name -> [count, total_s, max_s]

    def record(self, name: str, elapsed: float) -> None:
        with self._lock:
            row = self._stats.get(name)
            if row is None:
                row = self._stats[name] = [0, 0.0, 0.0]
            row[0] += 1
            row[1] += elapsed
            if elapsed > row[2]:
                row[2] = elapsed

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {
                name: {
                    "count": c,
                    "total_ms": round(t * 1e3, 3),
                    "mean_ms": round(t / c * 1e3, 3) if c else 0.0,
                    "max_ms": round(mx * 1e3, 3),
                }
                for name, (c, t, mx) in sorted(self._stats.items())
            }


HANDLER_STATS = HandlerStats()


class _GenericHandler(grpc.GenericRpcHandler):
    def __init__(self, handlers: Dict[str, Callable[[Any], Any]]):
        self._handlers = handlers

    def service(self, handler_call_details):
        name = handler_call_details.method.rsplit("/", 1)[-1]
        fn = self._handlers.get(name)
        if fn is None:
            return None

        def unary(request_bytes, context):
            t0 = time.perf_counter()
            try:
                req = cloudpickle.loads(request_bytes)
                return cloudpickle.dumps((True, fn(req)))
            except BaseException as exc:  # noqa: BLE001 - shipped to caller
                try:
                    return cloudpickle.dumps((False, exc))
                except Exception:  # unpicklable exception
                    return cloudpickle.dumps((False, RuntimeError(repr(exc))))
            finally:
                HANDLER_STATS.record(name, time.perf_counter() - t0)

        return grpc.unary_unary_rpc_method_handler(
            unary,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )


class RpcServer:
    """One gRPC server hosting named unary handlers.

    ``handlers`` maps method name -> fn(request_obj) -> response_obj.
    """

    def __init__(
        self,
        handlers: Dict[str, Callable[[Any], Any]],
        host: str = "127.0.0.1",
        port: int = 0,
        max_workers: int = 32,
    ):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_OPTIONS,
        )
        self._server.add_generic_rpc_handlers((_GenericHandler(handlers),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise RpcError(f"could not bind RPC server on {host}:{port}")
        self.address = f"{host}:{self.port}"
        self._server.start()

    def stop(self, grace: float = 0.2) -> None:
        self._server.stop(grace)


class RpcClient:
    """Channel to one peer; ``call(method, payload)`` round-trips an object."""

    def __init__(self, address: str):
        self.address = address
        self._channel = grpc.insecure_channel(address, options=_OPTIONS)
        self._methods: Dict[str, Any] = {}

    def _method(self, name: str):
        m = self._methods.get(name)
        if m is None:
            m = self._channel.unary_unary(
                f"/rtpu/{name}",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            self._methods[name] = m
        return m

    def call(
        self,
        method: str,
        payload: Any = None,
        timeout: Optional[float] = 30.0,
        retries: int = 0,
        retry_interval: float = 0.1,
    ) -> Any:
        import random

        data = cloudpickle.dumps(payload)
        attempt = 0
        # exponential backoff with decorrelated jitter: each sleep draws
        # uniform in [base, 3*prev], capped — retry bursts from many
        # callers desynchronize instead of hammering a recovering peer in
        # lockstep (retryable_grpc_client.cc exponential-backoff analog;
        # the previous linear `interval * attempt` ramp kept every waiter
        # phase-aligned).
        backoff = retry_interval
        cap = max(retry_interval, _BACKOFF_CAP_S)
        while True:
            try:
                _get_chaos().apply(method)
                raw = self._method(method)(data, timeout=timeout)
                ok, value = pickle.loads(raw)
                if not ok:
                    raise value
                return value
            except (grpc.RpcError, _ChaosDrop) as exc:
                if attempt >= retries:
                    raise RpcError(
                        f"rpc {method} to {self.address} failed: "
                        f"{exc.code() if hasattr(exc, 'code') else exc}"
                    ) from exc
                attempt += 1
                backoff = min(
                    cap,
                    random.uniform(
                        retry_interval, max(retry_interval, 3.0 * backoff)
                    ),
                )
                time.sleep(backoff)

    def close(self) -> None:
        self._channel.close()
