"""Owner-sharded control-plane tables.

The head's object directory and task/peer-link lease tables used to be
monolithic dicts. :class:`ShardedTable` splits one table into N fixed
shards behind a thin routing layer: every key routes to exactly one
shard by a stable hash (ids are minted per owner, so key-hash sharding
partitions the table by owner-affinity without needing the owner in the
key). Two properties fall out of the fixed routing:

- **Horizontal scaling seam** — lookups touch one shard; per-shard
  iteration (``shard_items``) lets future work move shards off-process
  without changing a single call site (the table keeps the full dict
  protocol).
- **Conflict-free WAL replay** — a WAL record that mutates key K only
  ever touches ``shard_of(K)``, so records routed to different shards
  commute: a standby replaying a shipped WAL stream can apply shard
  groups independently (``group_records_by_shard``) and still converge
  to the exact monolithic-replay state (asserted by
  tests/test_head_failover.py routing-equivalence tests).

The head's global lock still serializes mutations today; sharding here
is structural (routing + partitioning), not a locking change.
"""
from __future__ import annotations

import zlib
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple


def shard_of(key: str, num_shards: int) -> int:
    """The ONE routing function: stable across processes and restarts
    (crc32, not Python hash — PYTHONHASHSEED must not re-route a key),
    so the leader, its standbys, and every replay agree on placement."""
    if num_shards <= 1:
        return 0
    if isinstance(key, str):
        key = key.encode()
    return zlib.crc32(key) % num_shards


class ShardedTable:
    """Dict-compatible table split across fixed hash-routed shards."""

    __slots__ = ("_shards", "num_shards")

    def __init__(self, num_shards: int = 8):
        self.num_shards = max(1, int(num_shards))
        self._shards: List[dict] = [{} for _ in range(self.num_shards)]

    # -- routing layer --------------------------------------------------
    def shard_index(self, key: str) -> int:
        return shard_of(key, self.num_shards)

    def shard_for(self, key: str) -> dict:
        return self._shards[shard_of(key, self.num_shards)]

    def shard_items(self, index: int):
        return self._shards[index].items()

    def shard_sizes(self) -> List[int]:
        return [len(s) for s in self._shards]

    # -- dict protocol --------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self.shard_for(key)[key]

    def __setitem__(self, key: str, value: Any) -> None:
        self.shard_for(key)[key] = value

    def __delitem__(self, key: str) -> None:
        del self.shard_for(key)[key]

    def __contains__(self, key: str) -> bool:
        return key in self.shard_for(key)

    def __len__(self) -> int:
        return sum(len(s) for s in self._shards)

    def __bool__(self) -> bool:
        return any(self._shards)

    def __iter__(self) -> Iterator[str]:
        for s in self._shards:
            yield from s

    def get(self, key: str, default: Any = None) -> Any:
        return self.shard_for(key).get(key, default)

    def pop(self, key: str, *default) -> Any:
        return self.shard_for(key).pop(key, *default)

    def setdefault(self, key: str, default: Any = None) -> Any:
        return self.shard_for(key).setdefault(key, default)

    def keys(self):
        for s in self._shards:
            yield from s.keys()

    def values(self):
        for s in self._shards:
            yield from s.values()

    def items(self):
        for s in self._shards:
            yield from s.items()

    def clear(self) -> None:
        for s in self._shards:
            s.clear()

    def update(self, other) -> None:
        items = other.items() if hasattr(other, "items") else other
        for k, v in items:
            self[k] = v

    def as_dict(self) -> dict:
        return {k: v for k, v in self.items()}

    def __eq__(self, other) -> bool:
        if isinstance(other, ShardedTable):
            return self.as_dict() == other.as_dict()
        if isinstance(other, dict):
            return self.as_dict() == other
        return NotImplemented

    def __ne__(self, other) -> bool:
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"ShardedTable(shards={self.num_shards}, "
            f"sizes={self.shard_sizes()})"
        )


def group_records_by_shard(
    records,
    key_of: Callable[[Tuple[Any, ...]], Optional[str]],
    num_shards: int,
) -> Tuple[Dict[int, list], list]:
    """Partition a WAL record stream for conflict-free replay: records
    whose mutated key routes to different shards commute, so they group
    into independently-applicable per-shard lists (intra-shard order
    preserved — that is the order that matters). Records ``key_of``
    cannot route (cross-table or unknown kinds) land in the ordered
    residue and must apply sequentially."""
    groups: Dict[int, list] = {}
    residue: list = []
    for rec in records:
        key = key_of(rec)
        if key is None:
            residue.append(rec)
        else:
            groups.setdefault(shard_of(key, num_shards), []).append(rec)
    return groups, residue
