"""Head server: the cluster control plane (GCS analog).

One process per cluster, the equivalent of the reference's ``gcs_server``
(/root/reference/src/ray/gcs/gcs_server.h:255-319): node membership + health
checks, the object directory, the actor directory, placement groups with
2-phase commit, an internal KV store — and, unlike the reference, the *task*
scheduler too: every lease in the cluster is placed here by the batched
JAX hybrid kernel over the dense global resource view (the north-star
design — the raylet's per-request ``ScheduleAndGrantLeases`` scan,
cluster_lease_manager.cc:196, becomes one batched kernel call per round).
Agents keep authoritative per-node ledgers and grant-or-reject, so a stale
view degrades into spillback-and-retry exactly like the reference
(local_lease_manager.h:39-61).
"""
from __future__ import annotations

import logging
import os
import pickle
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.scheduler import (
    ClusterView,
    HybridConfig,
    ResourceRequest,
    ResourceVocab,
    hybrid_schedule_reference,
    schedule_bundles,
)
from ray_tpu.scheduler.hybrid import hardest_first_order
from ray_tpu.scheduler.device import (
    SCHED_KERNEL_MS,
    SCHED_READBACK_MS,
    SCHED_UPLOAD_MS,
    DeviceSchedulerState,
    device_scheduler_default,
)

from .common import (
    INLINE_OBJECT_MAX,
    ActorInfo,
    LeaseRequest,
    NodeInfo,
    NodeReport,
    SealInfo,
    new_id,
    stream_item_id,
)
from .object_plane import PEER_CONN_GRANTED, PEER_CONN_REVOKED
from .replication import ReplicationHub, set_role
from .rpc import RpcClient, RpcError, RpcNotLeaderError, RpcServer
from .shards import ShardedTable

logger = logging.getLogger("ray_tpu.cluster.head")


def _trace_args(spec) -> dict:
    from ray_tpu.util.tracing import event_args

    return event_args(getattr(spec, "trace", None))

from ray_tpu.config import cfg

SCHED_TICK_S = cfg.sched_tick_s
MAX_BATCH = cfg.sched_max_batch


from ray_tpu.util.metrics import Counter as _MetricCounter
from ray_tpu.util.metrics import Histogram as _MetricHistogram

# best-effort callbacks the head dropped (chaos runs watch this: a swallowed
# recovery error is invisible in logs at default level but not in metrics)
HEAD_DROPPED_CALLBACKS = _MetricCounter(
    "head_dropped_callbacks",
    "Best-effort head-side callbacks that raised and were swallowed.",
    label_names=("callable",),
)

# scheduler-loop round latency (until now only sched_rounds counted; a
# slow round — XLA bring-up, deep batch — was invisible)
SCHED_ROUND_MS = _MetricHistogram(
    "sched_round_ms",
    "Head scheduler loop round latency in ms (rounds with work only).",
    boundaries=(0.5, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 5000),
)

# task-lease lifecycle (lease-cached direct dispatch: the head grants
# worker leases to owners; tasks stream caller->worker off the head path)
TASK_LEASE_GRANTED = _MetricCounter(
    "task_lease_granted_total",
    "Worker leases granted to task owners for direct dispatch.",
)
TASK_LEASE_RETURNED = _MetricCounter(
    "task_lease_returned_total",
    "Worker leases returned by their owners (queue drain / idle TTL).",
)
TASK_LEASE_REVOKED = _MetricCounter(
    "task_lease_revoked_total",
    "Worker leases revoked by the head (worker/node death, TTL expiry, "
    "owner disconnect).",
)

# recursive lineage reconstruction (depth 0 = the requested object's own
# creating lease; depth N = a lost input N generations up the chain)
OBJECTS_RECONSTRUCTED = _MetricCounter(
    "objects_reconstructed_total",
    "Objects rebuilt by re-executing their creating lease, by lineage "
    "depth of the reconstruction walk that requeued them.",
    label_names=("depth",),
)
RECONSTRUCTION_MS = _MetricHistogram(
    "reconstruction_ms",
    "Latency from an object's loss being detected to its re-seal.",
    boundaries=(10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 15000, 60000),
)

# owner fate-sharing
OWNERS_REAPED = _MetricCounter(
    "owners_reaped_total",
    "Owner sessions reaped, by how the owner left (disconnect|crash).",
    label_names=("mode",),
)

# locality-scored placement (ISSUE 13): specs placed WITH residency data
# and the summed fraction of their input bytes already resident on the
# chosen node. hit_frac_total / scored_total == the plane's locality
# hit-rate (bytes served same-node / total input bytes, in expectation).
SCHED_LOCALITY_SCORED = _MetricCounter(
    "sched_locality_scored_total",
    "Leases placed while carrying a per-node input-residency vector "
    "(sched_w_locality > 0 and located, sized deps).",
)
SCHED_LOCALITY_HIT_FRAC = _MetricCounter(
    "sched_locality_hit_frac_total",
    "Sum over locality-scored placements of the fraction of the "
    "lease's input bytes resident on its chosen node.",
)

# preemption / migration (ISSUE 7): the kernel nominates a victim node
# per starving shape; the head kills-and-requeues concrete victims there
SCHED_PREEMPT_NOMINATED = _MetricCounter(
    "sched_preempt_nominated_total",
    "Preemption nominations emitted by the round/ring kernels (starving "
    "shape with unmet demand and zero capacity anywhere).",
)
SCHED_PREEMPTIONS = _MetricCounter(
    "sched_preemptions_total",
    "Victim leases actually preempted, by victim class (queued = "
    "cancelled before start, requeued attempt-free; worker_lease = "
    "revoked, owner spills; running = force-killed retryable task, "
    "requeued attempt-free through the lineage machinery).",
    label_names=("kind",),
)
GANG_EPOCH_BUMPS = _MetricCounter(
    "gang_epoch_bumps_total",
    "Gang-epoch advances in the elastic-training membership protocol, "
    "by cause (node_death = a member's node was declared dead by the "
    "health loop; fence = owner-requested fence, e.g. resize/grow or "
    "actor-level death observed driver-side; register = a new gang "
    "generation registered its membership).",
    label_names=("reason",),
)


def _shape_key_of(spec) -> tuple:
    """Memoized resource-shape identity of a spec — the ONE key the
    dense-row cache, the fair-batch classes, and the device ring all
    index by (they must agree, so there is exactly one derivation)."""
    key = getattr(spec, "_shape_key", None)
    if key is None:
        key = tuple(sorted(spec.resources.items()))
        spec._shape_key = key
    return key


def _best_effort(fn, *args, **kwargs):
    try:
        fn(*args, **kwargs)
    except Exception:  # noqa: BLE001
        # label by callable (+ rpc method when fn is RpcClient.call): the
        # name set is small and fixed, so metric cardinality stays bounded
        name = getattr(fn, "__name__", None) or repr(fn)
        if args and isinstance(args[0], str):
            name = f"{name}:{args[0]}"
        HEAD_DROPPED_CALLBACKS.inc(labels={"callable": name})
        logger.debug("best-effort call %s dropped", name, exc_info=True)


# One writer per persist path per process: restart_head() keeps the old and
# new HeadServer in the same process for a moment; the old instance must not
# overwrite the new instance's snapshots with stale state.
_PERSIST_LOCKS: Dict[str, threading.Lock] = {}
_PERSIST_OWNER: Dict[str, int] = {}
_PERSIST_REG_LOCK = threading.Lock()


@dataclass
class _ObjEntry:
    """Object-directory row (ownership_object_directory analog) — also the
    cluster-wide refcount row (reference_counter.h:44 analog): the head is
    the single ownership authority in this centralized design."""

    event: threading.Event = field(default_factory=threading.Event)
    inline: Optional[bytes] = None
    error: Optional[bytes] = None
    locations: set = field(default_factory=set)
    size: int = 0
    creating_lease: Optional[str] = None
    # holder process id -> count (negative transients tolerate a release
    # overtaking its matching borrow report on the wire)
    holders: Dict[str, int] = field(default_factory=dict)
    # in-flight lease arg pins + containing-object pins
    pins: int = 0
    # return-object owner hold registered (exactly once across the direct
    # seal path, its at-least-once retries, AND a head-path fallback lease)
    owner_registered: bool = False
    # ids of ObjectRefs serialized inside this object's sealed value
    contained: List[str] = field(default_factory=list)
    # a holder/pin was registered at least once. Entries that were never
    # tracked (e.g. seals reported to a freshly-restarted head, whose
    # refcount tables died with the old head) are exempt from GC — they
    # leak-until-shutdown instead of being wrongly freed.
    tracked: bool = False


@dataclass
class _PGState:
    pg_id: str
    bundles: List[Dict[str, float]]
    strategy: str
    ready: threading.Event = field(default_factory=threading.Event)
    node_per_bundle: List[str] = field(default_factory=list)
    removed: bool = False
    # soft anti-affinity (gang-aware reshape placement): prefer not to
    # land bundles on these nodes — the kernel first runs with them
    # masked out and falls back to the full cluster when the masked
    # placement is infeasible
    avoid_nodes: List[str] = field(default_factory=list)


class HeadServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        use_device_scheduler: Optional[bool] = None,
        dashboard_port: Optional[int] = None,
        persist_path: Optional[str] = None,
        persist_backend: Optional[Any] = None,
    ):
        self.vocab = ResourceVocab()
        self.view = ClusterView(self.vocab)
        self.hybrid_config = HybridConfig()
        if use_device_scheduler is None:
            use_device_scheduler = device_scheduler_default()
        self.use_device_scheduler = use_device_scheduler
        from ray_tpu.scheduler.device import LazyDeviceState

        self._lazy_device = LazyDeviceState(use_device_scheduler)
        # pipelined rounds (scheduler/pipeline.py): created lazily on the
        # scheduler thread at the first device round; None means rounds
        # are synchronous (RAY_TPU_SCHED_PIPELINE=0 or host golden model)
        self._pipeline = None
        # specs mid-flight in a dispatched-but-uncompleted pipelined round:
        # still pending demand for the autoscaler, already popped from
        # every scannable queue
        self._deferred_rounds: Dict[int, List[LeaseRequest]] = {}
        self._parked_at_change = -1
        self._last_park_retry = 0.0
        # per-shape dense demand rows at the current resource-axis width
        # (_round_shapes); None value = oversized/infeasible at this width
        self._dense_cache: Tuple[int, Dict[tuple, Optional[np.ndarray]]] = (
            -1,
            {},
        )
        self._rng = np.random.default_rng(0)
        self._seed = 0
        self._spread_rr = 0  # SPREAD round-robin cursor
        self._label_rr = 0  # label-selector tie-break cursor

        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self.nodes: Dict[str, NodeInfo] = {}
        self._clients: Dict[str, RpcClient] = {}
        self._last_report: Dict[str, float] = {}
        # owner-sharded object directory (shards.py): dict-compatible,
        # but every lookup routes to one fixed shard and shipped-WAL
        # replay partitions by the same routing
        self._objects: ShardedTable = ShardedTable(cfg.head_shards)
        self._leases: Dict[str, LeaseRequest] = {}  # lineage: lease_id -> spec
        # --- distributed refcounting state ---
        from ray_tpu.core.refcount import FreedLRU

        self._freed = FreedLRU()
        self._holder_hexes: Dict[str, set] = {}  # holder -> ids it counts
        self._lease_arg_pins: Dict[str, List[str]] = {}  # lease -> pinned args
        self._lease_live_returns: Dict[str, int] = {}  # lease -> unfreed outs
        self._pending: deque = deque()
        self._infeasible: List[LeaseRequest] = []
        self._scheduling_batch: List[LeaseRequest] = []
        # lease ids cancelled while mid-schedule: dropped at dispatch time
        # (the round already popped them out of every scannable queue)
        self._cancelled_leases: set = set()
        # --- starvation / preemption state (ISSUE 7) ---
        # per-shape wait age in park-retry rounds: bumped every time a
        # round leaves the shape (partly) unplaced, cleared when the
        # shape's parked queue fully drains. Normalized by
        # cfg.sched_starve_rounds and uploaded with the demand rows
        # (kernel term d: starvation discount + preemption arming).
        self._shape_wait: Dict[tuple, int] = {}
        # lease ids whose running worker the head force-killed to
        # preempt: the agent's worker-death "failed" report requeues them
        # WITHOUT consuming a retry attempt (a preemption is a scheduler
        # action, not a task failure)
        self._preempted_leases: set = set()
        # per-shape monotonic deadline before the next preemption action
        # (freed capacity takes an agent report round-trip to appear)
        self._preempt_cooldown: Dict[tuple, float] = {}
        self._in_flight: Dict[str, Tuple[LeaseRequest, str]] = {}
        # streaming-generator state: task_id -> {"items": [hex...],
        # "done": bool, "consumed": int, "touched": monotonic}
        # (object_ref_generator.py analog; items arrive via ReportSeals
        # "stream" entries, consumers long-poll WaitStream)
        self._streams: Dict[str, dict] = {}
        self._stream_cv = threading.Condition()
        # drained/GC'd stream ids: a late WaitStream reads "done" instead
        # of parking forever on a stream that will never reappear
        self._stream_tombstones: set = set()
        self._stream_tombstone_order: deque = deque()
        # task-lease table (lease-cached direct dispatch): lease_id ->
        # {state: granting|active, resources, client_id, fn_id, node_id,
        #  worker_address, worker_id, accel_env, expires_at, abandoned}.
        # Active entries persist in the snapshot/WAL so TTL expiry and
        # revoke-on-death survive a head restart (owners keep streaming
        # to their leased workers regardless — the head is off that path).
        # Owner-sharded like the object directory.
        self._task_leases: ShardedTable = ShardedTable(cfg.head_shards)
        self._grant_gate = threading.BoundedSemaphore(8)
        # peer-link lease table (cross-node data plane, transport.py):
        # link_id -> {link_id, src, dst, endpoint, granted_at,
        # expires_at}. The grant hands the requester the destination's
        # data endpoint + auth token ONCE per (src, dst) pair;
        # steady-state transfers then make zero head RPCs. Rows persist
        # in the snapshot/WAL (granted links keep serving across a head
        # restart), renew via piggybacked agent reports, and are revoked
        # on either endpoint node's death.
        self._peer_links: ShardedTable = ShardedTable(cfg.head_shards)
        self._peer_links_by_pair: Dict[tuple, str] = {}
        # revocation fan-outs queued as WAL records (revoke_pending /
        # revoke_done): a promoted standby or restarted head re-drives
        # any the dying leader never delivered, idempotently, instead of
        # trusting the corpse's best-effort last breaths.
        self._pending_revokes: Dict[str, dict] = {}
        self._actors: Dict[str, ActorInfo] = {}
        self._actor_specs: Dict[str, LeaseRequest] = {}
        self._named_actors: Dict[str, str] = {}
        self._actor_send: Dict[str, deque] = {}  # per-actor ordered sender
        self._actor_sending: set = set()
        self._pgs: Dict[str, _PGState] = {}
        self._pending_pgs: List[_PGState] = []
        self._pgs_dirty = True  # retry pending PGs only after view changes
        self._kv: Dict[str, bytes] = {}
        self._jobs: Dict[str, dict] = {}
        # owner liveness (session leases): client_id -> {"last", "strikes",
        # "last_strike"}. Registered by ClientHello / first owner_beat;
        # reaped by _check_owner_liveness on missed strikes or by a clean
        # DisconnectClient.
        self._owner_sessions: Dict[str, dict] = {}
        # objects whose loss has been detected and whose rebuild is in
        # flight: oid -> (t0, depth) — dedups concurrent reconstruction
        # triggers and feeds the reconstruction metrics on re-seal
        self._reconstructing: Dict[str, tuple] = {}
        self._shutdown = False
        self._persist_path = persist_path
        self._persist_dirty = False
        self._lineage_dirty_at = 0.0  # rate gate for per-lease dirtying
        self._wal_queue: deque = deque()
        # pluggable persistence (store_client analog): any object with
        # load/save_snapshot/wal_append/wal_replay; FilePersistence default
        self._backend = persist_backend
        if persist_backend is not None and not persist_path:
            persist_path = f"<backend:{id(persist_backend)}>"
            self._persist_path = persist_path
        if persist_path and self._backend is None:
            from .persistence import FilePersistence

            self._backend = FilePersistence(persist_path)
        if self._backend is not None:
            # lock/owner registration guards EVERY backend (a custom one
            # too), or _wal_flush/_persist_now KeyError on first use
            with _PERSIST_REG_LOCK:
                _PERSIST_LOCKS.setdefault(persist_path, threading.Lock())
                _PERSIST_OWNER[persist_path] = id(self)
        from ray_tpu.core.events import TaskEventBuffer

        self.events = TaskEventBuffer()
        self._recovered_epoch = 0
        # router-fleet assignment tables (horizontally scaled ingress):
        # deployment -> {"epoch": int, "members": [router_id]}. The
        # epoch is the fence for every fleet control RPC — a deposed
        # router's late acquire/ckpt/budget traffic is rejected exactly
        # like stale cluster-epoch stamps. Durable (snapshot + WAL) so
        # a promoted standby keeps fencing the same epochs.
        self._serve_fleets: Dict[str, dict] = {}
        # weights-version epochs (online-RL publish fence): deployment ->
        # {"committed": int, "meta": dict, "sealed": {"epoch", "meta"}|None}.
        # Publish is two-phase (seal -> commit), each phase its own WAL
        # record replicated to standbys, so a head killed mid-publish
        # leaves either the old or the new epoch fully visible — never a
        # torn in-between. Fenced exactly like gang epochs: commit of an
        # epoch that is not the currently sealed one is rejected stale.
        self._weights_epochs: Dict[str, dict] = {}
        # fleet stream leases: stream_id -> {stream_id, deployment,
        # tenant, router_id, delivered, ts}. The delivered-count
        # checkpoints are what make router failover token-exact — a
        # sibling inheriting the hash range resumes from here. Sharded
        # + WAL-persisted like task leases / peer links.
        self._serve_streams: ShardedTable = ShardedTable(cfg.head_shards)
        if persist_path:
            self._load_persisted()
        # cluster epoch (epoch-fenced control plane): strictly increases
        # across head incarnations — the persisted epoch + 1 when a
        # snapshot survives, floored by wall-clock millis so even an
        # UNPERSISTED restart (or a lost snapshot) still fences out
        # pre-restart traffic. Agents/owners adopt it at registration and
        # stamp their control RPCs; stale stamps are rejected before any
        # handler can touch the rebuilt tables.
        self.cluster_epoch = max(
            int(self._recovered_epoch) + 1, int(time.time() * 1000.0)
        )
        # control-plane replication (replication.py): WAL records and
        # snapshot barriers ship to registered warm standbys; this head
        # is the leader until it observes a higher epoch and fences
        # itself (role: leader -> fenced; a fenced head refuses writes).
        self.role = "leader"
        self._fenced = False
        self._leader_hint = ""
        self._repl = ReplicationHub(self)
        set_role("leader")
        self.metrics: Dict[str, int] = {
            "leases_submitted": 0,
            "leases_finished": 0,
            "leases_spilled_back": 0,
            "sched_rounds": 0,
            "nodes_dead": 0,
            "task_leases_granted": 0,
            "task_leases_returned": 0,
            "task_leases_revoked": 0,
            "peer_links_granted": 0,
            "peer_links_revoked": 0,
            "preempt_nominations": 0,
            "preemptions": 0,
        }
        # serving-plane state reported by ingress routers (1/s control
        # traffic, never per-request): (client_id, deployment) -> blob.
        # Ephemeral by design — a restarted head repopulates within one
        # report period.
        self._serve_state: Dict[tuple, dict] = {}
        # per-deployment router budget reports (ephemeral — one
        # reconcile window repopulates): dep -> rid -> report
        self._serve_budget: Dict[str, dict] = {}
        # last serve-pressure capacity verdict per deployment (PR 18):
        # dep -> {"hint": {...}|None, "ts"} — advisory, ephemeral
        self._serve_capacity_hints: Dict[str, dict] = {}
        # elastic-training gang membership: gang_id -> {"epoch", "owner",
        # "members" {rank -> node_id}, "min_size", "dead_ranks", "updated"}.
        # The epoch is the fence for every gang collective — stragglers
        # from a dead epoch are rejected at the rendezvous exactly like
        # stale control RPCs at the cluster fence. Ephemeral like
        # _serve_state: the owning driver re-registers (with an epoch
        # floor) after a head failover, and re-registration itself bumps
        # the epoch, so a pre-failover straggler can never pass the fence.
        self._gangs: Dict[str, dict] = {}
        # nodes mid drain-ahead (PR 19): node_id -> monotonic deadline.
        # While a node drains, NodeReport's advertised availability is
        # clamped to zero so no loop — legacy or unified — schedules new
        # work onto a machine the provider is about to reclaim.
        self._draining_nodes: Dict[str, float] = {}
        # metrics federation (ISSUE 15): typed registry deltas shipped by
        # agents (their workers' relayed through them) merge here,
        # namespaced by node/role labels; the dashboard /metrics scrape
        # renders this plus the head's own registry. Ephemeral like
        # _serve_state: senders keep shipping deltas to whichever head
        # is leading, so a restarted head's accumulation restarts at the
        # fault boundary (counters are since-head-start, documented).
        from ray_tpu.util.metrics import FederatedRegistry
        from ray_tpu.util.metrics import Gauge as _MetricGauge

        self.federation = FederatedRegistry()
        # created eagerly: a lazy first-scrape construction would race
        # the dashboard executor against the crash-bundle pool, and a
        # loser's instance could shadow the registry slot forever
        self._node_avail_gauge = _MetricGauge(
            "ray_tpu_node_available",
            "Per-node available resource quantity.",
            ("node", "resource"),
        )
        # scheduler decision attribution: task_id -> explanation (the
        # five per-term cost contributions of the winning placement),
        # bounded FIFO (cfg.sched_explain_keep)
        self._explain: "OrderedDict[str, dict]" = OrderedDict()
        self._explain_lock = threading.Lock()

        self._dispatch_pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="head-dispatch"
        )
        handlers = {
            "RegisterNode": self._h_register_node,
            "NodeReport": self._h_node_report,
            "ReportSeals": self._h_report_seals,
            "SubmitLease": self._h_submit_lease,
            "ClientBatch": self._h_client_batch,
            "PutObject": self._h_put_object,
            "WaitObject": self._h_wait_object,
            "LocateObjects": self._h_locate_objects,
            "ObjectSizes": self._h_object_sizes,
            "WaitObjectBatch": self._h_wait_object_batch,
            "WaitStream": self._h_wait_stream,
            "StreamConsumed": self._h_stream_consumed,
            "StreamAbandon": self._h_stream_abandon,
            "FreeObjects": self._h_free_objects,
            "RefUpdate": lambda r: self._h_ref_update(r, src="direct"),
            "GrantTaskLease": self._h_grant_task_lease,
            "GrantPeerLink": self._h_grant_peer_link,
            "ReturnPeerLink": self._h_return_peer_link,
            # drivers renew directly (agents piggyback on ReportSeals)
            "RenewPeerLinks": lambda r: self._renew_peer_links(
                r.get("link_ids", ())
            ),
            "CreateActor": self._h_create_actor,
            "GetActor": self._h_get_actor,
            "WaitActor": self._h_wait_actor,
            "PendingDemands": self._h_pending_demands,
            "CancelLease": self._h_cancel_lease,
            "KillActor": self._h_kill_actor,
            "DisconnectClient": self._h_disconnect_client,
            "ClientHello": self._h_client_hello,
            "ObjectMissing": self._h_object_missing,
            "CreatePlacementGroup": self._h_create_pg,
            "WaitPlacementGroup": self._h_wait_pg,
            "RemovePlacementGroup": self._h_remove_pg,
            "KvPut": self._h_kv_put,
            "KvGet": lambda r: self._kv.get(r["key"]),
            "KvDel": self._h_kv_del,
            "KvKeys": lambda r: [
                k for k in self._kv if k.startswith(r.get("prefix", ""))
            ],
            "ClusterInfo": self._h_cluster_info,
            "GangRegister": self._h_gang_register,
            "GangSync": self._h_gang_sync,
            "GangFence": self._h_gang_fence,
            "GangUnregister": self._h_gang_unregister,
            "GangHint": self._h_gang_hint,
            "ReportServeState": self._h_report_serve_state,
            "ServeFleetJoin": self._h_serve_fleet_join,
            "ServeFleetLeave": self._h_serve_fleet_leave,
            "ServeAssignment": self._h_serve_assignment,
            "ServeStreamAcquire": self._h_serve_stream_acquire,
            "ServeStreamCkpt": self._h_serve_stream_ckpt,
            "ServeStreamRelease": self._h_serve_stream_release,
            "ServeStreamLookup": self._h_serve_stream_lookup,
            "ServeBudget": self._h_serve_budget,
            "WeightsPublishSeal": self._h_weights_publish_seal,
            "WeightsPublishCommit": self._h_weights_publish_commit,
            "WeightsEpochGet": self._h_weights_epoch_get,
            "QueryState": self._h_query_state,
            "StandbyHello": self._h_standby_hello,
            "HeadRole": self._h_head_role,
            "Timeline": lambda r: self.events.dump_timeline(None),
            "SubmitJob": lambda r: self.jobs.submit(
                entrypoint=r["entrypoint"],
                runtime_env=r.get("runtime_env"),
                submission_id=r.get("submission_id"),
                metadata=r.get("metadata"),
            ),
            "JobStatus": lambda r: self.jobs.status(r["job_id"]),
            "JobLogs": lambda r: self.jobs.logs(r["job_id"]),
            "ListJobs": lambda r: self.jobs.list(),
            "StopJob": lambda r: self.jobs.stop(r["job_id"]),
            "Ping": lambda r: "pong",
        }
        # jobs must exist before the RPC server accepts its first request:
        # a SubmitJob/ListJobs arriving in the gap would hit AttributeError.
        # JobManager needs the head address, which is only known after bind,
        # so construct it lazily-addressed and fill in below.
        from .jobs import JobManager

        self.jobs = JobManager(None, on_change=self.mark_dirty)
        self._server = RpcServer(handlers, host=host, port=port)
        if cfg.epoch_fencing:
            self._server.epoch = self.cluster_epoch
            # the resync protocol itself must pass the fence: RegisterNode
            # re-attaches an agent (and hands out the new epoch),
            # ClientHello does the same for owners, Ping is liveness,
            # StandbyHello/HeadRole are the replication bootstrap + role
            # probe (a standby has no epoch to stamp yet)
            self._server.fence_exempt = {
                "RegisterNode",
                "ClientHello",
                "Ping",
                "StandbyHello",
                "HeadRole",
            }
            # a request stamped with a HIGHER epoch proves a newer head
            # incarnation exists: step down (self-fence) immediately
            self._server.on_newer_epoch = self._observed_newer_epoch
        self.address = self._server.address
        self.jobs.head_address = self.address
        for job in getattr(self, "_recovered_jobs", []):
            self.jobs.restore(job)
        self.dashboard = None
        if dashboard_port is not None:
            from .dashboard import Dashboard

            self.dashboard = Dashboard(self, host=host, port=dashboard_port)

        # unified elasticity plane (PR 19): constructed always (so a
        # provider can attach and QueryState can introspect), ticking
        # only when cfg.elastic_controller is on — OFF leaves the three
        # legacy loops (autoscaler, serve SLO, gang grow probe) as the
        # sole capacity authorities, bit-for-bit.
        from ray_tpu.scheduler.elasticity import ElasticityController

        self._elasticity = ElasticityController(self)
        if cfg.elastic_controller:
            self._elasticity.start()

        self._sched_thread = threading.Thread(
            target=self._scheduler_loop, name="head-scheduler", daemon=True
        )
        self._health_thread = threading.Thread(
            target=self._health_loop, name="head-health", daemon=True
        )
        self._sched_thread.start()
        self._health_thread.start()
        if persist_path:
            threading.Thread(
                target=self._persist_loop, name="head-persist", daemon=True
            ).start()

    # ------------------------------------------------------------------
    # state persistence (GCS fault tolerance analog: the reference persists
    # its tables to Redis, store_client/redis_store_client.cc; here a
    # debounced pickle snapshot of the durable tables — KV, jobs, and the
    # actor directory; live actors re-attach when agents re-register)
    # ------------------------------------------------------------------
    def _snapshot_state(self) -> dict:
        # streams first, OUTSIDE self._lock: _snapshot_streams takes
        # _stream_cv then (separately) _lock — nesting it under _lock here
        # would invert _h_wait_stream's (_stream_cv -> _lock) order
        streams_part = self._snapshot_streams()
        with self._lock:
            return {
                # the NEXT incarnation starts at a strictly higher epoch
                "epoch": self.cluster_epoch,
                "kv": dict(self._kv),
                "named_actors": dict(self._named_actors),
                "actors": {
                    a.actor_id: dict(vars(a)) for a in self._actors.values()
                },
                "actor_specs": dict(self._actor_specs),
                "jobs": self.jobs.snapshot() if hasattr(self, "jobs") else [],
                # lineage: the head is this design's ownership authority,
                # so task lineage must survive it the way the reference's
                # owner workers survive a GCS restart. Without this, an
                # object whose only copy dies AFTER a head restart is
                # unrecoverable (no spec to re-execute). Debounced with
                # the rest of the snapshot; a hard crash can lose the
                # last ~1s of lineage, a clean restart loses none.
                "leases": {
                    lid: spec
                    for lid, spec in self._leases.items()
                    if spec.kind == "task" and spec.return_ids
                },
                # active task leases: TTL expiry / revoke-on-death keep
                # working across a restart (owners stream direct anyway)
                "task_leases": [
                    self._lease_snapshot_row(e)
                    for e in self._task_leases.values()
                    if e["state"] == "active"
                ],
                # granted peer data links: revocation/expiry bookkeeping
                # survives a restart (the links themselves keep serving
                # head-free; tokens re-learn from re-registration)
                "peer_links": [
                    self._peer_link_row(e) for e in self._peer_links.values()
                ],
                # undelivered revocation fan-outs: a successor re-drives
                # them (idempotent receiver-side) instead of relying on
                # this process's best-effort sends having landed
                "pending_revokes": {
                    rid: dict(row)
                    for rid, row in self._pending_revokes.items()
                },
                # router-fleet assignment epochs + stream-lease ckpts:
                # a restarted head must keep fencing the same epochs
                # and resuming streams token-exact
                "serve_fleets": {
                    dep: dict(f) for dep, f in self._serve_fleets.items()
                },
                # weights-version publish fence: committed epoch + any
                # sealed-but-uncommitted phase survive restart/promotion
                # so the publisher's retry resolves to exactly one epoch
                "weights_epochs": {
                    dep: dict(w) for dep, w in self._weights_epochs.items()
                },
                "serve_streams": [
                    dict(row) for row in self._serve_streams.values()
                ],
            } | streams_part

    def _snapshot_streams(self) -> dict:
        """Streaming-generator state for the snapshot: a head restart with
        unconsumed items must not strand the consumer's WaitStream loop.
        Inline item values ride along (they live nowhere else — large
        items re-advertise from node stores on agent re-registration)."""
        with self._stream_cv:
            streams = {
                tid: {
                    "items": list(st["items"]),
                    "done": st["done"],
                    "consumed": st["consumed"],
                    "delivered": st["delivered"],
                    "abandoned": bool(st.get("abandoned")),
                }
                for tid, st in self._streams.items()
            }
            tombstones = list(self._stream_tombstone_order)
        inline: Dict[str, tuple] = {}
        with self._lock:
            for st in streams.values():
                for oid in st["items"]:
                    e = self._objects.get(oid)
                    if e is None:
                        continue
                    if e.inline is not None:
                        inline[oid] = ("inline", e.inline)
                    elif e.error is not None:
                        inline[oid] = ("error", e.error)
        return {
            "streams": streams,
            "stream_tombstones": tombstones,
            "stream_inline": inline,
        }

    def _wal(self, record: tuple) -> None:
        """Queue a durable registration for the WAL. Called UNDER
        self._lock so queue order matches memory-mutation order; the
        actual disk append happens in _wal_flush() AFTER the head lock is
        released — taking the persist lock here would invert the
        persist-thread's (persist lock -> head lock) order and deadlock
        the whole head."""
        if self._backend is None:
            return
        self._wal_queue.append(record)

    def _wal_flush(self) -> None:
        """Drain queued WAL records to disk (call with self._lock NOT
        held) and publish them to the replication stream. Records drain
        in queue order regardless of which handler thread flushes, so
        replay order always matches acknowledged state; the replication
        seq is assigned under the same persist lock, so shipped order
        matches disk order."""
        if self._backend is None or not self._wal_queue:
            return
        if self._fenced:
            # a deposed leader writes nothing: not to disk, not to the
            # stream — its late mutations must be provably rejected
            self._wal_queue.clear()
            return
        lock = _PERSIST_LOCKS[self._persist_path]
        with lock:
            if _PERSIST_OWNER.get(self._persist_path) != id(self):
                self._wal_queue.clear()
                return
            records = []
            while True:
                try:
                    records.append(self._wal_queue.popleft())
                except IndexError:
                    break
            for record in records:
                try:
                    self._backend.wal_append(record)
                except Exception:  # noqa: BLE001 - durability best-effort
                    logger.exception("WAL append failed")
            last_seq = self._repl.publish(records)
        # acked shipping (cfg.wal_ship_acked) waits OUTSIDE the persist
        # lock: the shipper thread never takes it, but other handlers'
        # flushes must not serialize behind this one's ack wait
        if last_seq and cfg.wal_ship_acked:
            self._repl.wait_acked(
                last_seq, timeout=cfg.wal_ship_ack_timeout_s
            )

    def _load_persisted(self) -> None:
        snap = self._backend.load() or {}
        records = self._backend.wal_replay()
        if not snap and not records:
            return
        self._recovered_epoch = int(snap.get("epoch", 0))
        self._kv = dict(snap.get("kv", {}))
        self._named_actors = dict(snap.get("named_actors", {}))
        self._actor_specs = dict(snap.get("actor_specs", {}))
        # recovered lineage: pre-create directory entries wired to their
        # creating leases (unsealed, no locations — agents re-advertise
        # the bytes on re-registration). Untracked entries are GC-exempt,
        # consistent with all refcount state that predates a restart.
        for lid, spec in snap.get("leases", {}).items():
            self._leases[lid] = spec
            for rid in spec.return_ids:
                entry = self._objects.setdefault(rid, _ObjEntry())
                entry.creating_lease = lid
        # streaming-generator state: restored so consumers' WaitStream
        # loops pick up where they left off. Inline item values are
        # re-seeded here; store-resident items regain locations when
        # their agents re-register.
        now = time.monotonic()
        for tid, st in snap.get("streams", {}).items():
            self._streams[tid] = {**st, "touched": now}
        for tid in snap.get("stream_tombstones", []):
            self._tombstone_stream(tid)
        for oid, (kind, blob) in snap.get("stream_inline", {}).items():
            entry = self._objects.setdefault(oid, _ObjEntry())
            if kind == "error":
                entry.error = blob
            else:
                entry.inline = blob
                entry.size = len(blob)
            entry.event.set()
        now_m = time.monotonic()
        ttl = cfg.task_lease_ttl_s
        for row in snap.get("task_leases", []):
            self._restore_task_lease(row, now_m, ttl)
        for row in snap.get("peer_links", []):
            self._restore_peer_link(row)
        for rid, row in snap.get("pending_revokes", {}).items():
            self._pending_revokes[rid] = dict(row)
        for dep, f in snap.get("serve_fleets", {}).items():
            self._serve_fleets[dep] = {
                "epoch": int(f.get("epoch", 0)),
                "members": list(f.get("members", ())),
            }
        for dep, w in snap.get("weights_epochs", {}).items():
            self._weights_epochs[dep] = {
                "committed": int(w.get("committed", 0)),
                "meta": dict(w.get("meta", {})),
                "sealed": dict(w["sealed"]) if w.get("sealed") else None,
            }
        for row in snap.get("serve_streams", []):
            self._serve_streams[row["stream_id"]] = dict(row)
        for actor_id, fields in snap.get("actors", {}).items():
            info = ActorInfo(**fields)
            # hosting agents re-register and re-attach; until then, unknown
            if info.state != "DEAD":
                info.state = "RESTARTING"
                info.node_id = None
                info.address = None
            self._actors[actor_id] = info
        self._recovered_jobs = snap.get("jobs", [])
        # replay registrations that landed after the last snapshot tick
        for rec in records:
            kind = rec[0]
            if kind == "kv_put":
                self._kv[rec[1]] = rec[2]
            elif kind == "kv_del":
                self._kv.pop(rec[1], None)
            elif kind == "actor":
                fields, spec, name = rec[1], rec[2], rec[3]
                info = ActorInfo(**fields)
                if info.state != "DEAD":
                    info.state = "RESTARTING"
                    info.node_id = None
                    info.address = None
                self._actors[info.actor_id] = info
                if spec is not None:
                    self._actor_specs[info.actor_id] = spec
                if name:
                    self._named_actors[name] = info.actor_id
            elif kind == "actor_dead":
                info = self._actors.get(rec[1])
                if info is not None:
                    info.state = "DEAD"
                    if (
                        info.name
                        and self._named_actors.get(info.name) == rec[1]
                    ):
                        del self._named_actors[info.name]
            elif kind == "task_lease":
                self._restore_task_lease(
                    rec[1], time.monotonic(), cfg.task_lease_ttl_s
                )
            elif kind == "task_lease_gone":
                self._task_leases.pop(rec[1], None)
            elif kind == "peer_link":
                self._restore_peer_link(rec[1])
            elif kind == "peer_link_gone":
                e = self._peer_links.pop(rec[1], None)
                if e is not None:
                    self._peer_links_by_pair.pop(
                        (e["src"], e["dst"]), None
                    )
            elif kind == "revoke_pending":
                self._pending_revokes[rec[1]["revoke_id"]] = dict(rec[1])
            elif kind == "revoke_done":
                self._pending_revokes.pop(rec[1], None)
            elif kind == "serve_fleet":
                row = rec[1]
                self._serve_fleets[row["deployment"]] = {
                    "epoch": int(row.get("epoch", 0)),
                    "members": list(row.get("members", ())),
                }
            elif kind == "serve_stream":
                row = dict(rec[1])
                self._serve_streams[row["stream_id"]] = row
            elif kind == "serve_stream_ckpt":
                row = self._serve_streams.get(rec[1]["stream_id"])
                if row is not None:
                    row["delivered"] = max(
                        int(row.get("delivered", 0)),
                        int(rec[1].get("delivered", 0)),
                    )
                    if rec[1].get("router_id"):
                        row["router_id"] = rec[1]["router_id"]
            elif kind == "serve_stream_gone":
                self._serve_streams.pop(rec[1], None)
            elif kind == "weights_epoch":
                self._replay_weights_epoch(rec[1])
        logger.info(
            "recovered head state: %d kv keys, %d actors, %d jobs, "
            "%d WAL records",
            len(self._kv),
            len(self._actors),
            len(self._recovered_jobs),
            len(records),
        )
        # owner sessions are in-memory only, so fate-sharing must survive
        # the restart: re-seed a session (fresh deadline) for every owner
        # the restored actors/leases reference. A live owner's next beat
        # keeps it fresh; one that crashed around the restart accrues
        # strikes and gets the full reap — otherwise its actors and
        # leases would leak forever and dependents would hang instead of
        # raising OwnerDiedError.
        if cfg.owner_liveness:
            owners = {
                info.owner_client
                for info in self._actors.values()
                if info.owner_client
                and info.lifetime != "detached"
                and info.state != "DEAD"
            }
            owners.update(
                e["client_id"]
                for e in self._task_leases.values()
                if e.get("client_id")
            )
            for cid in owners:
                self._touch_owner(cid)
        # actors recovered as RESTARTING normally re-attach when their
        # hosting agents re-register. One registered-but-never-created
        # (the WAL window) has NO hosting agent — after a grace period,
        # resubmit its creation lease or it parks RESTARTING forever.
        if any(a.state == "RESTARTING" for a in self._actors.values()):
            threading.Thread(
                target=self._recover_orphan_actors,
                name="head-actor-recover",
                daemon=True,
            ).start()

    def _restore_peer_link(self, row: dict) -> None:
        """Rebuild one persisted peer-link row (expiry rebased; at least
        one TTL of grace so live holders get a renewal in first)."""
        e = dict(row)
        remaining = float(e.pop("ttl_remaining_s", 0.0))
        e["expires_at"] = time.monotonic() + max(
            remaining, cfg.peer_link_ttl_s
        )
        self._peer_links[e["link_id"]] = e
        self._peer_links_by_pair[(e["src"], e["dst"])] = e["link_id"]

    def _restore_task_lease(self, row: dict, now_m: float, ttl: float) -> None:
        """Rebuild one persisted lease row (expiry rebased onto this
        process's monotonic clock; at least one TTL of grace so live
        owners get a renewal in before the sweep runs)."""
        e = dict(row)
        remaining = float(e.pop("ttl_remaining_s", 0.0))
        e["state"] = "active"
        e["abandoned"] = False
        e["expires_at"] = now_m + max(remaining, ttl)
        self._task_leases[e["lease_id"]] = e

    def _recover_orphan_actors(self, grace_s: float = 10.0) -> None:
        time.sleep(grace_s)
        to_create = []
        with self._cond:
            if self._shutdown:
                return
            for info in self._actors.values():
                if info.state != "RESTARTING" or info.node_id is not None:
                    continue
                spec = self._actor_specs.get(info.actor_id)
                if spec is None:
                    continue
                clone = LeaseRequest(
                    task_id=new_id(),
                    name=spec.name,
                    payload=spec.payload,
                    return_ids=[],
                    resources=spec.resources,
                    kind="actor_creation",
                    actor_id=info.actor_id,
                    max_retries=0,
                    strategy=spec.strategy,
                    runtime_env=spec.runtime_env,
                    actor_meta=spec.actor_meta,
                )
                to_create.append(clone)
                self._leases[clone.task_id] = clone
                self._pending.append(clone)
            if to_create:
                self._cond.notify_all()
        if to_create:
            logger.info(
                "resubmitting %d recovered actor creations with no "
                "hosting agent",
                len(to_create),
            )

    def mark_dirty(self) -> None:
        self._persist_dirty = True

    def _mark_hot_dirty(self) -> None:
        """Rate-gated mark_dirty for HOT paths (lease submission, stream
        item flow): dirtying per event would re-pickle the whole live
        lease/stream state at the 1s persist tick — O(in-flight) work per
        second on head threads. ~5s staleness is fine: clean restarts
        flush on shutdown; only a hard crash can lose the gap."""
        now = time.monotonic()
        if now - self._lineage_dirty_at > 5.0:
            self._lineage_dirty_at = now
            self.mark_dirty()

    def _persist_now(self) -> None:
        if self._fenced:
            return  # deposed: never overwrite the successor's state
        lock = _PERSIST_LOCKS[self._persist_path]
        with lock:
            if _PERSIST_OWNER.get(self._persist_path) != id(self):
                return  # a newer head owns this file now; never write stale
            try:
                snap = self._snapshot_state()
                self._backend.save_snapshot(snap)
                # snapshot barrier into the replication stream, still
                # under the persist lock: a record that mutated AFTER
                # this capture cannot be sequenced before the barrier
                # (its flush needs this same lock), so a standby
                # applying [.., barrier, record..] never loses it
                self._repl.publish_snapshot(snap)
            except Exception:  # noqa: BLE001
                self._persist_dirty = True  # don't lose the write; retry
                logger.exception("head state persistence failed")

    def _persist_loop(self) -> None:
        while True:
            time.sleep(1.0)
            if self._shutdown or self._fenced:
                return  # shutdown() does the final flush itself
            if not self._persist_dirty:
                continue
            self._persist_dirty = False
            self._persist_now()

    # ------------------------------------------------------------------
    # control-plane replication: WAL shipping to warm standbys + fenced
    # leadership (replication.py, standby.py)
    # ------------------------------------------------------------------
    def _h_standby_hello(self, req: dict) -> dict:
        """Standby bootstrap: register it for WAL shipping and hand back
        a full snapshot + the stream position it covers. The seq is read
        BEFORE the capture, so records racing the capture are both in
        the snapshot and shipped again — double-applied (idempotent),
        never lost."""
        if self._fenced:
            raise RpcNotLeaderError(
                "this head is fenced (deposed leader)",
                leader_hint=self._leader_hint,
            )
        if self._backend is None:
            # no persistence stream to ship: a standby of this head
            # would bootstrap once and silently never converge again
            raise RuntimeError(
                "WAL shipping requires head persistence "
                "(start the head with persist_path/persist_backend)"
            )
        from_seq = self._repl.seq
        # register BEFORE capturing: records flushed during the capture
        # are retained for shipping AND already inside the snapshot —
        # double-applied (idempotent), never lost
        self._repl.register_standby(
            req["standby_id"], req["address"], from_seq
        )
        snap = self._snapshot_state()
        return {
            "snapshot": snap,
            "from_seq": from_seq,
            "epoch": self.cluster_epoch,
            "leader": self.address,
        }

    def _h_head_role(self, req) -> dict:
        """Leadership probe (fence-exempt, served even while fenced):
        agents/clients walk their head-candidate list with this when the
        configured head stops answering as leader."""
        return {
            "role": self.role,
            "epoch": self.cluster_epoch,
            "leader_hint": self._leader_hint,
            "address": self.address,
        }

    def _observed_newer_epoch(self, epoch: int) -> None:
        """RPC-layer callback: a request arrived stamped with a HIGHER
        epoch than ours — proof a newer head incarnation exists (its
        sender registered there). Self-fence immediately."""
        self._step_down(epoch, "request stamped with a newer epoch")

    def _step_down(
        self, new_epoch: int, why: str, leader_hint: str = ""
    ) -> None:
        """Deposed-leader self-fencing: refuse every write from here on.
        Mutating RPCs are rejected at the server layer with
        RpcNotLeaderError (callers walk to the real leader), internal
        loops exit, and neither the snapshot file nor the WAL is ever
        written again — the successor owns them. The process stays up
        only to redirect stragglers."""
        with self._lock:
            if self._fenced or self._shutdown:
                return
            self._fenced = True
            self.role = "fenced"
            if leader_hint:
                self._leader_hint = leader_hint
        set_role("fenced")
        logger.warning(
            "head %s stepping down (epoch %d observed > ours %d): %s",
            self.address,
            int(new_epoch),
            self.cluster_epoch,
            why,
        )
        self._server.role_hint = "fenced"
        self._server.not_leader_hint = self._leader_hint or None
        self._server.refuse_non_leader = True
        self._repl.stop()
        # wake the scheduler loop so it observes the fence and exits
        with self._cond:
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # membership + health (GcsNodeManager / GcsHealthCheckManager analog)
    # ------------------------------------------------------------------
    def _h_kv_put(self, r: dict) -> None:
        with self._lock:
            # queue under the same lock as the memory write: replay order
            # must match acknowledged state (two racing puts to one key)
            self._kv[r["key"]] = r["value"]
            self._wal(("kv_put", r["key"], r["value"]))
        self._wal_flush()
        self.mark_dirty()

    def _h_kv_del(self, r: dict) -> None:
        with self._lock:
            self._kv.pop(r["key"], None)
            self._wal(("kv_del", r["key"]))
        self._wal_flush()
        self.mark_dirty()

    def _h_register_node(self, info: NodeInfo) -> dict:
        with self._cond:
            self.nodes[info.node_id] = info
            old_client = self._clients.get(info.node_id)
            # breaker -> health path: a wedged/blackholed transport to this
            # node opens its circuit and declares it unreachable in
            # ~rpc_breaker_window_s instead of stalling every dispatch for
            # its full timeout (the 600s accelerator-transport wedge class)
            self._clients[info.node_id] = RpcClient(
                info.address,
                on_unreachable=lambda nid=info.node_id: (
                    self._peer_unreachable(nid)
                ),
            )
            if old_client is not None:
                # in-flight calls on the old channel fail with RpcError and
                # take the normal retry paths; never leak channels on rejoin
                old_client.close()
            self._last_report[info.node_id] = time.monotonic()
            self.view.add_node(info.node_id, info.resources, info.labels)
            # fresh capacity may unblock parked leases / pending PGs
            self._pending.extend(self._infeasible)
            self._infeasible.clear()
            self._pgs_dirty = True
            self._cond.notify_all()
        # re-attach actors this agent still hosts (head-restart recovery:
        # the actor instances kept running in the agent's workers)
        for meta in info.hosted_actors:
            actor_id = meta["actor_id"]
            with self._lock:
                existing = self._actors.get(actor_id)
                if existing is None:
                    name = meta.get("name")
                    self._actors[actor_id] = ActorInfo(
                        actor_id=actor_id,
                        name=name,
                        node_id=info.node_id,
                        address=info.address,
                        state="ALIVE",
                        max_restarts=meta.get("max_restarts", 0),
                        lifetime=meta.get("lifetime"),
                        owner_client=meta.get("owner_client", ""),
                    )
                    if name and name not in self._named_actors:
                        self._named_actors[name] = actor_id
                    continue
            # _mark_actor_alive handles the DEAD case by tearing the
            # zombie instance down on the agent
            self._mark_actor_alive(actor_id, info.node_id, info.address)
        # re-seed the object directory from the agent's store inventory
        # (head-restart recovery: the directory died with the old head but
        # the bytes live on in node stores). Entries new to this head stay
        # untracked — exempt from GC exactly like any refcount state that
        # predates a restart — while entries the head already tracks just
        # regain a location.
        if info.stored_objects:
            self._apply_seals(
                [
                    SealInfo(
                        object_id=oid, node_id=info.node_id, size=int(size)
                    )
                    for oid, size in info.stored_objects
                ]
            )
        # task-lease reconciliation: leases the agent still holds that
        # this head no longer tracks (unpersisted restart, WAL window)
        # are released so their workers don't stay pinned forever
        for lid in getattr(info, "held_task_leases", ()) or ():
            with self._lock:
                known = lid in self._task_leases
                if known:
                    # re-learn the hosting node (snapshot rows survive,
                    # but a row restored before agents re-registered may
                    # predate a node-id change)
                    self._task_leases[lid]["node_id"] = info.node_id
            if not known:
                logger.info(
                    "agent %s holds unknown task lease %s; releasing",
                    info.node_id,
                    lid[:8],
                )
                self._agent_return_lease(info.node_id, lid)
        # re-drive any revocation fan-out queued for this node that a
        # previous incarnation (or an earlier outage window) never
        # delivered — idempotent on the agent side
        self._redrive_revokes(info.node_id)
        logger.info("node %s registered at %s", info.node_id, info.address)
        return {
            "node_id": info.node_id,
            "head_address": self.address,
            # adopted by the agent: its control RPCs stamp this epoch, so
            # a future head restart fences it until it re-registers
            "epoch": self.cluster_epoch,
        }

    def _peer_unreachable(self, node_id: str) -> None:
        """Circuit breaker opened on this peer: its transport has been
        failing for the whole server-unavailable window. Feed the health
        path immediately — leases requeue, actors restart, and the agent
        (if actually alive behind a one-way partition) re-registers on its
        next report once the path heals."""
        if self._shutdown:
            return
        with self._lock:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return
        logger.warning(
            "rpc circuit to node %s opened; marking unreachable", node_id
        )
        self._on_node_death(node_id)

    def _h_node_report(self, report: NodeReport) -> dict:
        with self._cond:
            self._last_report[report.node_id] = time.monotonic()
            node = self.nodes.get(report.node_id)
            alive = node is not None and node.alive
            draining = report.node_id in self._draining_nodes
            if alive:
                avail = report.available
                if draining:
                    # drain-ahead: a retiring node advertises zero so no
                    # scheduling path lands new work on it mid-drain
                    avail = {k: 0.0 for k in (avail or {})}
                self.view.update_available(report.node_id, avail)
                self._pgs_dirty = True
        if report.seals:
            self._apply_seals(report.seals)
        if report.finished_leases:
            self._finish_leases(report.finished_leases)
        # alive=False tells an agent that was (transiently) declared dead to
        # re-register — nodes can rejoin after a heartbeat gap. draining=True
        # tells the agent to stop warming its pool (PR 19 drain-ahead).
        return {"alive": alive, "draining": draining}

    def _health_loop(self) -> None:
        """Strike-based liveness (gcs_health_check_manager.h analog:
        period x failure_threshold): a node is dead only after
        ``health_miss_threshold`` CONSECUTIVE missed windows of
        ``health_timeout_s / threshold`` each — total detection latency
        stays ~health_timeout_s, but one wall-clock gap (GC pause,
        transfer storm on a loaded host) no longer kills a healthy node.
        The poll period is jittered so co-located heads (tests, multi-head
        hosts) don't phase-align their scans."""
        import random as _random

        rng = _random.Random(0x4EA17)
        strikes: Dict[str, int] = {}
        last_strike: Dict[str, float] = {}
        while not self._shutdown and not self._fenced:
            threshold = max(1, int(cfg.health_miss_threshold))
            window = cfg.health_timeout_s / threshold
            time.sleep(window / 2.0 * rng.uniform(0.7, 1.3))
            now = time.monotonic()
            dead = []
            with self._lock:
                known = set(self.nodes)
                for nid, node in self.nodes.items():
                    if not node.alive:
                        continue
                    gap = now - self._last_report.get(nid, now)
                    if gap <= window:
                        strikes.pop(nid, None)
                        last_strike.pop(nid, None)
                        continue
                    # one strike per window, not per poll: the poll runs
                    # ~2x per window, and a single long gap must not be
                    # double-counted into an instant death
                    if now - last_strike.get(nid, 0.0) >= window * 0.9:
                        strikes[nid] = strikes.get(nid, 0) + 1
                        last_strike[nid] = now
                    if strikes.get(nid, 0) >= threshold:
                        dead.append(nid)
            for nid in list(strikes):
                if nid not in known:
                    strikes.pop(nid, None)
                    last_strike.pop(nid, None)
            for nid in dead:
                strikes.pop(nid, None)
                last_strike.pop(nid, None)
                logger.warning(
                    "node %s missed %d consecutive health windows; "
                    "marking dead",
                    nid,
                    threshold,
                )
                self._on_node_death(nid)
            self._gc_idle_streams()
            self._expire_task_leases()
            self._expire_peer_links()
            self._check_owner_liveness()
            self._expire_pending_revokes()
            self._expire_serve_streams()

    def _expire_serve_streams(self) -> None:
        """Reap fleet stream-lease rows whose owner stopped
        checkpointing (consumer crashed without release): a bounded
        leak, mirroring task-lease TTL expiry. The TTL is generous —
        a live stream checkpoints every reconcile window."""
        ttl = max(60.0, 40 * float(cfg.serve_budget_reconcile_s))
        now = time.time()
        with self._lock:
            stale = [
                sid
                for sid, row in self._serve_streams.items()
                if now - float(row.get("ts") or now) > ttl
            ]
            for sid in stale:
                self._serve_streams.pop(sid, None)
                self._wal(("serve_stream_gone", sid))
        if stale:
            self._wal_flush()

    def _on_node_death(self, node_id: str) -> None:
        with self._cond:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return
            node.alive = False
            self.metrics["nodes_dead"] += 1
            self.view.remove_node(node_id)
            lost_leases = [
                (lid, spec)
                for lid, (spec, nid) in self._in_flight.items()
                if nid == node_id
            ]
            for lid, _ in lost_leases:
                self._in_flight.pop(lid, None)
            # every object ADVERTISED on the dead node, not only those
            # whose locations are exactly {node_id}: _recover_object
            # prunes the stale row either way, and reconstructs only when
            # no live copy remains — a multi-copy object whose replica
            # nodes die one by one would otherwise keep its stale rows
            # forever and never rebuild
            lost_objects = [
                oid
                for oid, e in self._objects.items()
                if node_id in e.locations and e.inline is None
            ]
            dead_actors = [
                a for a in self._actors.values() if a.node_id == node_id
            ]
            # task leases on the dead node: revoke (the owners' channels
            # discover via RPC failure and spill their queues to the
            # per-task head path — chaos-safe by construction)
            dead_leases = [
                lid
                for lid, e in self._task_leases.items()
                if e.get("node_id") == node_id
            ]
            for lid in dead_leases:
                self._drop_task_lease_locked(lid)
                self.metrics["task_leases_revoked"] += 1
                TASK_LEASE_REVOKED.inc()
            self._cond.notify_all()
        # peer data links touching the dead node: revoke + notify holders
        self._revoke_node_peer_links(node_id)
        # elastic gangs with a member on the corpse: advance their epochs
        # so the membership protocol fences the dead generation
        self._gangs_note_node_death(node_id)
        # in-flight leases on the dead node: retry or fail
        requeued = set()
        for lid, spec in lost_leases:
            requeued.add(lid)
            self._retry_or_fail(spec, f"node {node_id} died running {spec.name}")
        # objects whose only copy died: lineage reconstruction — requeue each
        # creating lease ONCE even if several of its returns were lost
        for oid in lost_objects:
            self._recover_object(oid, node_id, requeued)
        # actors: restart state machine (GcsActorManager analog)
        for info in dead_actors:
            self._restart_or_kill_actor(info, f"node {node_id} died")

    def _retry_or_fail(self, spec: LeaseRequest, reason: str) -> None:
        if spec.kind == "worker_lease":
            # a grant lost in flight (agent unreachable / node died):
            # drop the table row — the waiting owner's long-poll returns
            # "grant failed" and it stays on the per-task head path
            with self._cond:
                e = self._task_leases.get(spec.task_id)
                was_active = e is not None and e["state"] == "active"
                self._drop_task_lease_locked(spec.task_id)
                if was_active:
                    self.metrics["task_leases_revoked"] += 1
                    TASK_LEASE_REVOKED.inc()
                self._cond.notify_all()
            self._wal_flush()
            return
        if spec.kind == "actor_creation":
            # a creation lease lost to node death / unreachable agent is a
            # SCHEDULING failure, not an actor failure: reschedule without
            # consuming the actor's restart budget (GcsActorScheduler
            # reschedule-on-node-death analog). Without this, an actor
            # whose hosting node died mid-creation parked PENDING forever
            # (found by the chaos soak's early kill_node fault).
            info = self._actors.get(spec.actor_id)
            if info is not None and info.state != "DEAD":
                logger.info(
                    "actor %s creation lost (%s); rescheduling",
                    spec.actor_id,
                    reason,
                )
                spec.target_node = None
                with self._cond:
                    self._pending.append(spec)
                    self._cond.notify_all()
                return
            self._release_lease_pins(spec.task_id)
            return
        if spec.kind == "actor_method":
            self._seal_error_ids(spec.return_ids, RuntimeError(reason))
            if spec.streaming:
                # streaming methods have no return ids; without this the
                # consumer's WaitStream long-poll would never end
                self._fail_stream(spec, reason)
            self._release_lease_pins(spec.task_id)
            return
        with self._cond:
            preempted = spec.task_id in self._preempted_leases
            self._preempted_leases.discard(spec.task_id)
        if preempted or spec.attempt < spec.max_retries:
            # a victim whose preemption kill raced node death still
            # requeues attempt-free (the kill was the scheduler's doing)
            if not preempted:
                spec.attempt += 1
            spec.target_node = None
            with self._cond:
                self.metrics["leases_spilled_back"] += 1
                self._pending.append(spec)
                self._cond.notify_all()
        else:
            self._seal_error_ids(spec.return_ids, RuntimeError(reason))
            if spec.streaming:
                self._fail_stream(spec, reason)
            self._release_lease_pins(spec.task_id)
            # a task that burned its whole retry budget is a post-mortem
            # moment: snapshot the flight recorder while the evidence
            # (events, spans, metrics) is still in the windows
            self._dump_crash_bundle(
                f"task-retries-exhausted-{spec.task_id[:8]}"
            )

    def _recover_object(
        self, object_id: str, dead_node: str, requeued: set
    ) -> None:
        with self._lock:
            entry = self._objects.get(object_id)
            if entry is None:
                return
            entry.locations.discard(dead_node)
            if not self._object_lost_locked(entry):
                return
        self._reconstruct_object(
            object_id, f"node {dead_node} died", requeued=requeued
        )

    def _object_lost_locked(self, entry: _ObjEntry) -> bool:
        """A sealed value with no reachable copy. Caller holds self._lock.
        Entries still being produced (never sealed, no locations) are NOT
        lost — their creating lease is already in flight."""
        if entry.inline is not None or entry.error is not None:
            return False
        if entry.locations:
            return not any(
                nid in self.nodes and self.nodes[nid].alive
                for nid in entry.locations
            )
        return entry.event.is_set()

    def _note_reconstructing(self, object_id: str, depth: int) -> None:
        with self._lock:
            if object_id not in self._reconstructing:
                self._reconstructing[object_id] = (time.monotonic(), depth)

    def _reconstruct_object(
        self,
        object_id: str,
        reason: str,
        depth: int = 0,
        requeued: Optional[set] = None,
    ) -> None:
        """Recursive lineage reconstruction (the reference's
        ObjectRecoveryManager walk): requeue the lost object's creating
        lease — and, FIRST, the lineage of any of its inputs that are
        also lost, so the requeued lease's dependency wait resolves.
        Depth-bounded by ``cfg.reconstruction_max_depth``; attempt-bounded
        per lease by ``max_retries`` (``max_retries=0`` keeps strict
        at-most-once semantics: the object fails instead of re-executing);
        concurrent triggers for one object dedup through ``requeued`` and
        the already-pending check."""
        from ray_tpu.core.object_store import ObjectLostError

        if requeued is None:
            requeued = set()
        max_depth = max(0, int(cfg.reconstruction_max_depth))
        if depth > max_depth:
            self._seal_error_ids(
                [object_id],
                ObjectLostError(
                    f"object {object_id} lost ({reason}); rebuilding it "
                    f"needs more than reconstruction_max_depth={max_depth} "
                    "generations of lineage re-execution"
                ),
            )
            return
        with self._cond:
            entry = self._objects.get(object_id)
            if entry is None or not self._object_lost_locked(entry):
                return
            entry.event.clear()  # getters park until the re-seal (or error)
            lease_id = entry.creating_lease
            spec = self._leases.get(lease_id) if lease_id else None
            pending_already = lease_id is not None and (
                lease_id in requeued
                or lease_id in self._in_flight
                or any(s.task_id == lease_id for s in self._pending)
                or any(s.task_id == lease_id for s in self._scheduling_batch)
                or any(
                    s.task_id == lease_id
                    for specs in self._deferred_rounds.values()
                    for s in specs
                )
            )
        if spec is None or spec.kind != "task":
            self._seal_error_ids(
                [object_id],
                ObjectLostError(
                    f"object {object_id} lost ({reason}); no re-executable "
                    "lineage (not produced by a plain task)"
                ),
            )
            return
        self._note_reconstructing(object_id, depth)
        if pending_already:
            # one rebuild of this lease re-seals every lost sibling
            # return; this trigger just joins the in-flight attempt
            return
        if spec.attempt >= spec.max_retries:
            why = (
                "max_retries=0 (at-most-once): refusing to re-execute"
                if spec.max_retries == 0
                else f"lineage retries exhausted ({spec.max_retries})"
            )
            self._seal_error_ids(
                [object_id],
                ObjectLostError(f"object {object_id} lost ({reason}); {why}"),
            )
            if spec.max_retries > 0:
                self._dump_crash_bundle(
                    f"lineage-retries-exhausted-{spec.task_id[:8]}"
                )
            return
        # lost INPUTS first: the requeued lease parks in dependency wait
        # until they re-seal, so their lineage must be re-executing too
        for arg in dict.fromkeys(spec.arg_ids):
            with self._lock:
                if arg in self._freed:
                    broken = True
                    arg_lost = False
                else:
                    broken = False
                    ae = self._objects.get(arg)
                    arg_lost = ae is not None and self._object_lost_locked(ae)
            if broken:
                # an input was already GC'd: the chain cannot re-execute
                self._seal_error_ids(
                    [object_id],
                    ObjectLostError(
                        f"object {object_id} lost ({reason}); lineage "
                        f"input {arg} was already freed"
                    ),
                )
                return
            if arg_lost:
                self._reconstruct_object(
                    arg,
                    f"lineage input of {object_id[:8]}",
                    depth=depth + 1,
                    requeued=requeued,
                )
        requeued.add(lease_id)
        logger.info(
            "reconstructing object %s (depth %d, attempt %d/%d): %s",
            object_id[:8],
            depth,
            spec.attempt + 1,
            spec.max_retries,
            reason,
        )
        spec.attempt += 1
        spec.target_node = None
        with self._cond:
            self._pending.append(spec)
            self._cond.notify_all()

    def _h_object_missing(self, req: dict) -> None:
        """A fetcher found an advertised copy definitively absent (the
        peer answered without the object — evicted, lost mid-spill, or a
        stale directory row): prune those locations, and if that was the
        last reachable copy, rebuild through lineage. Transient fetch
        failures never land here."""
        oid = req["object_id"]
        with self._lock:
            e = self._objects.get(oid)
            if e is None:
                return
            for nid in req.get("node_ids") or ():
                e.locations.discard(nid)
            lost = self._object_lost_locked(e)
        if lost:
            self._reconstruct_object(oid, "all advertised copies missing")

    def chaos_drop_objects(self, object_ids: List[str]) -> int:
        """Chaos fault: destroy every stored copy of the given sealed
        objects and drop their directory locations BEFORE driving
        recovery — so a chain dropped together exercises the recursive
        walk (an object whose inputs are also gone). Returns how many
        were actually dropped."""
        victims: List[Tuple[str, Any, str]] = []
        dropped: List[str] = []
        with self._lock:
            for oid in object_ids:
                e = self._objects.get(oid)
                if (
                    e is None
                    or e.inline is not None
                    or e.error is not None
                    or not e.locations
                ):
                    continue
                victims.extend(
                    (nid, self._clients.get(nid), oid)
                    for nid in list(e.locations)
                )
                e.locations.clear()
                dropped.append(oid)
        for nid, client, oid in victims:
            if client is not None:
                _best_effort(
                    client.call, "DeleteObjects", {"object_ids": [oid]}
                )
        requeued: set = set()
        for oid in dropped:
            self._reconstruct_object(oid, "<chaos drop>", requeued=requeued)
        return len(dropped)

    def chaos_drop_object(self, object_id: str) -> bool:
        """Single-object drop (see chaos_drop_objects). Returns False for
        objects that can't be meaningfully dropped (unknown,
        inline-valued, or never sealed)."""
        return self.chaos_drop_objects([object_id]) == 1

    def _restart_or_kill_actor(self, info: ActorInfo, reason: str) -> None:
        with self._lock:
            if info.state == "DEAD":
                return
            spec = self._actor_specs.get(info.actor_id)
            if spec is not None and info.num_restarts < info.max_restarts:
                info.num_restarts += 1
                info.state = "RESTARTING"
                info.node_id = None
                info.address = None
                restart = True
            else:
                info.state = "DEAD"
                restart = False
                # release the name so a replacement can rebind it
                if info.name and self._named_actors.get(info.name) == info.actor_id:
                    del self._named_actors[info.name]
                # death must out-survive a WAL'd registration, or recovery
                # resurrects a killed actor from the log
                self._wal(("actor_dead", info.actor_id))
            # wake WaitActor long-polls (push-based actor-state plane)
            self._cond.notify_all()
        self._wal_flush()
        self.mark_dirty()
        if not restart and spec is not None:
            # the actor is gone for good: its ctor args no longer need to
            # outlive it (the lifetime pin from _h_create_actor)
            self._release_lease_pins(spec.task_id)
        if restart:
            clone = LeaseRequest(
                task_id=new_id(),
                name=spec.name,
                payload=spec.payload,
                return_ids=[],
                resources=spec.resources,
                kind="actor_creation",
                actor_id=info.actor_id,
                max_retries=0,
                strategy=spec.strategy,
                runtime_env=spec.runtime_env,
            )
            with self._cond:
                self._pending.append(clone)
                self._cond.notify_all()
        else:
            logger.info("actor %s is dead: %s", info.actor_id, reason)

    # ------------------------------------------------------------------
    # object directory (ownership_object_directory + memory store analog)
    # ------------------------------------------------------------------
    def _entry(self, object_id: str) -> _ObjEntry:
        with self._lock:
            return self._objects.setdefault(object_id, _ObjEntry())

    def _apply_seals(self, seals: List[SealInfo]) -> None:
        check: List[str] = []
        stale: List[Tuple[str, str]] = []  # (node_id, object_id)
        with self._cond:
            for s in seals:
                if s.object_id in self._freed:
                    # every handle died before this seal/re-advertisement
                    # landed: the advertising node's copy must still be
                    # deleted or its shm leaks
                    if not s.is_error and s.node_id:
                        stale.append((s.node_id, s.object_id))
                    continue
                e = self._objects.setdefault(s.object_id, _ObjEntry())
                if s.owner and not e.owner_registered:
                    # direct-call return object: the caller is its holder
                    # (no lease ever registered one). Guarded: seal reports
                    # are at-least-once (worker retries on transport blips)
                    # and a fallback lease may also register the owner —
                    # counting twice would leak the object forever.
                    e.owner_registered = True
                    self._add_holder(s.object_id, s.owner)
                if s.is_error:
                    e.error = s.error
                else:
                    if s.inline_value is not None:
                        e.inline = s.inline_value
                    e.locations.add(s.node_id)
                    e.size = s.size
                    if s.contained_ids and not e.contained:
                        # nested-ref pinning: only the original seal carries
                        # contained ids (peer-fetch re-advertisements don't)
                        e.contained = list(s.contained_ids)
                        for inner in e.contained:
                            self._pin(inner)
                e.event.set()
                rec = self._reconstructing.pop(s.object_id, None)
                if rec is not None and not s.is_error:
                    t0, rec_depth = rec
                    RECONSTRUCTION_MS.observe((time.monotonic() - t0) * 1e3)
                    OBJECTS_RECONSTRUCTED.inc(
                        labels={"depth": str(rec_depth)}
                    )
                check.append(s.object_id)
            self._cond.notify_all()
        for nid, oid in stale:
            client = self._clients.get(nid)
            if client is not None:
                self._dispatch_pool.submit(
                    _best_effort,
                    client.call,
                    "DeleteObjects",
                    {"object_ids": [oid]},
                )
        # a seal may land after the last holder left: free immediately
        self._maybe_free_many(check)

    def _finish_leases(self, lease_ids: List[str]) -> None:
        unpin: List[str] = []
        with self._cond:
            for lid in lease_ids:
                self._in_flight.pop(lid, None)
                self.metrics["leases_finished"] += 1
                spec = self._leases.get(lid)
                if spec is not None:
                    self.events.record(
                        lid, spec.name, "FINISHED", **_trace_args(spec)
                    )
                # a restartable actor's ctor args stay pinned for the actor's
                # lifetime (lineage for restarts); released when it dies
                if spec is None or spec.kind != "actor_creation":
                    unpin.append(lid)
            # completed leases freed resources somewhere: notify the
            # scheduler loop, whose capacity-capped unpark retries parked
            # work. Draining the WHOLE parked queue here (pre-r5 behavior)
            # re-scheduled every parked spec on every completion batch —
            # O(parked²) churn that halved e2e throughput under a deep
            # backlog (BENCH_r04 654 tasks/s vs r03 1206.7).
            self._pgs_dirty = True
            self._cond.notify_all()
        for lid in unpin:
            self._release_lease_pins(lid)

    def _h_report_seals(self, req: dict) -> None:
        node_id = req.get("node_id")
        if node_id and req.get("available") is not None:
            with self._lock:
                node = self.nodes.get(node_id)
                if node is not None and node.alive:
                    self.view.update_available(node_id, req["available"])
        # metrics federation: typed registry deltas piggybacking on the
        # coalesced report (agent's own + its workers', pre-labeled)
        for ent in req.get("metrics", ()):
            try:
                self.federation.apply(
                    ent.get("node", node_id or ""),
                    ent.get("role", "agent"),
                    ent.get("records", ()),
                )
            except Exception:  # noqa: BLE001 - a bad record must not
                logger.exception("metrics federation apply failed")
        # borrows must land before the finished-lease unpin below: the pin is
        # what keeps a borrowed arg alive until its borrow is on the books
        if req.get("borrows"):
            self._apply_borrows(req["borrows"])
        self._apply_seals(req.get("seals", []))
        # stream entries AFTER their seals (same report): an item is only
        # announced once its object is resolvable
        if req.get("stream"):
            self._apply_stream_items(req["stream"])
        if req.get("stream_done"):
            self._apply_stream_done(req["stream_done"])
        if req.get("finished"):
            self._finish_leases(req["finished"])
        for holder in req.get("holders_gone", []):
            self._drop_holder(holder)
        for fail in req.get("failed", []):
            with self._cond:
                item = self._in_flight.pop(fail["task_id"], None)
            spec = item[0] if item else self._leases.get(fail["task_id"])
            if spec is None:
                continue
            if spec.task_id in self._cancelled_leases:
                self._cancelled_leases.discard(spec.task_id)
                continue  # force-cancel kill: already sealed cancelled
            preempted = spec.task_id in self._preempted_leases
            if preempted:
                # preemption kill (migration): a scheduler action, not a
                # task failure — requeue with NO retry attempt burned;
                # the next round places it on a different node
                with self._cond:
                    self._preempted_leases.discard(spec.task_id)
                    self.metrics["leases_spilled_back"] += 1
                    spec.target_node = None
                    self._pending.append(spec)
                    self._cond.notify_all()
                continue
            if fail.get("requeue"):
                # contention spillback: back to the queue, no retry burned
                with self._cond:
                    self.metrics["leases_spilled_back"] += 1
                    spec.target_node = None
                    self._pending.append(spec)
                    self._cond.notify_all()
                continue
            if fail.get("retryable", True):
                self._retry_or_fail(spec, fail.get("reason", "worker failure"))
            else:
                self._seal_error_ids(
                    spec.return_ids,
                    RuntimeError(fail.get("reason", "worker failure")),
                )
        for miss in req.get("objects_missing", ()):
            self._h_object_missing(miss)
        if req.get("task_leases"):
            self._apply_task_lease_reports(req["task_leases"])
        if req.get("peer_links"):
            # renew-while-hot: ids of links this agent used recently,
            # piggybacked on the coalesced report (no dedicated RPC)
            self._renew_peer_links(req["peer_links"])
        for actor_ready in req.get("actors_alive", []):
            self._mark_actor_alive(**actor_ready)
        for actor_dead in req.get("actors_dead", []):
            info = self._actors.get(actor_dead["actor_id"])
            if info is not None:
                self._restart_or_kill_actor(info, actor_dead.get("reason", ""))

    # ------------------------------------------------------------------
    # streaming generators (object_ref_generator.py analog)
    # ------------------------------------------------------------------
    def _stream_state(self, task_id: str) -> dict:
        """Caller holds self._stream_cv."""
        st = self._streams.get(task_id)
        if st is None:
            st = self._streams[task_id] = {
                "items": [],
                "done": False,
                "consumed": 0,
                "delivered": 0,  # holder-registration watermark
                "touched": time.monotonic(),
            }
        return st

    def _tombstone_stream(self, task_id: str) -> None:
        """Caller holds self._stream_cv."""
        if task_id not in self._stream_tombstones:
            self._stream_tombstones.add(task_id)
            self._stream_tombstone_order.append(task_id)
            while len(self._stream_tombstone_order) > 4096:
                self._stream_tombstones.discard(
                    self._stream_tombstone_order.popleft()
                )

    def _apply_stream_items(self, items: List[dict]) -> None:
        with self._stream_cv:
            for it in items:
                st = self._stream_state(it["task_id"])
                idx = it["index"]
                if idx == len(st["items"]):
                    st["items"].append(it["object_id"])
                # idx < len: a retried executor re-announced an item —
                # the re-seal already refreshed its location; nothing to do
                st["touched"] = time.monotonic()
            self._stream_cv.notify_all()
        self._mark_hot_dirty()  # stream state rides the debounced snapshot

    def _apply_stream_done(self, dones: List[dict]) -> None:
        with self._stream_cv:
            for d in dones:
                st = self._stream_state(d["task_id"])
                err = d.get("error")
                if err is not None and not st["done"]:
                    # mid-stream task failure: the next ref raises
                    oid = stream_item_id(d["task_id"], len(st["items"]))
                    self._apply_seals(
                        [
                            SealInfo(
                                object_id=oid,
                                node_id="",
                                is_error=True,
                                error=err,
                            )
                        ]
                    )
                    st["items"].append(oid)
                st["done"] = True
                st["touched"] = time.monotonic()
            self._stream_cv.notify_all()
        self._mark_hot_dirty()

    def _fail_stream(self, spec: LeaseRequest, reason: str) -> None:
        """Lease-level failure (worker/node death, retries exhausted)."""
        import pickle as _pickle

        self._apply_stream_done(
            [
                {
                    "task_id": spec.task_id,
                    "error": _pickle.dumps(RuntimeError(reason)),
                }
            ]
        )

    def _h_wait_stream(self, req: dict) -> dict:
        """Consumer long-poll for items past ``after``; ``after`` is also
        the consumption watermark that frees the executor's backpressure
        window (StreamConsumed)."""
        task_id = req["task_id"]
        after = int(req.get("after", 0))
        deadline = time.monotonic() + min(float(req.get("timeout", 2.0)), 30.0)
        with self._stream_cv:
            if task_id in self._stream_tombstones:
                # drained or GC'd: definitively over
                return {"items": [], "done": True}
            st = self._streams.get(task_id)
            if st is None:
                # not yet known: the pipelined lease submission (or the
                # first item) may still be in flight — wait for it
                while st is None:
                    wait_s = deadline - time.monotonic()
                    if wait_s <= 0:
                        return {"items": [], "done": False}
                    self._stream_cv.wait(timeout=min(wait_s, 0.5))
                    if task_id in self._stream_tombstones:
                        return {"items": [], "done": True}
                    st = self._streams.get(task_id)
            st["consumed"] = max(st["consumed"], after)
            st["touched"] = time.monotonic()
            self._stream_cv.notify_all()  # executor credit poll may wait
            while len(st["items"]) <= after and not st["done"]:
                wait_s = deadline - time.monotonic()
                if wait_s <= 0:
                    return {"items": [], "done": False}
                self._stream_cv.wait(timeout=min(wait_s, 0.5))
            items = st["items"][after:]
            done = st["done"]
            # holder registration is watermarked so an at-least-once
            # retried WaitStream can't double-count the consumer
            holder = req.get("holder")
            fresh = (
                st["items"][st["delivered"]:] if holder else []
            )
            st["delivered"] = max(st["delivered"], len(st["items"]))
            if done and st["consumed"] >= len(st["items"]) and not items:
                # fully drained: the generator saw StopIteration
                self._streams.pop(task_id, None)
                self._tombstone_stream(task_id)
        if fresh:
            # the consumer holds live refs the moment the reply lands;
            # count it as holder BEFORE replying so nothing frees the
            # items in between
            with self._lock:
                for oid in fresh:
                    self._add_holder(oid, holder)
        return {"items": items, "done": done}

    def _h_stream_consumed(self, req: dict) -> dict:
        """Executor credit poll. Long-polls until the consumer watermark
        moves past ``after_consumed`` (or the stream is abandoned) so a
        backpressured executor parks one request instead of spinning
        20 RPC/s through its agent."""
        after = req.get("after_consumed")
        deadline = time.monotonic() + min(
            float(req.get("timeout", 0.0) or 0.0), 30.0
        )
        with self._stream_cv:
            while True:
                st = self._streams.get(req["task_id"])
                if st is None:
                    # unknown/GC'd: report infinite credit so the executor
                    # can finish (its items free through normal GC)
                    return {"consumed": 1 << 62, "abandoned": True}
                if st.get("abandoned"):
                    return {"consumed": 1 << 62, "abandoned": True}
                if after is None or st["consumed"] > after:
                    return {"consumed": st["consumed"], "abandoned": False}
                wait_s = deadline - time.monotonic()
                if wait_s <= 0:
                    return {"consumed": st["consumed"], "abandoned": False}
                self._stream_cv.wait(timeout=min(wait_s, 0.5))

    def _h_stream_abandon(self, req: dict) -> None:
        """Best-effort consumer-drop notice (ObjectRefGenerator.__del__):
        opens the executor's window so it can't wedge on backpressure,
        and makes the stream eligible for idle GC."""
        with self._stream_cv:
            st = self._streams.get(req["task_id"])
            if st is not None:
                st["abandoned"] = True
                st["done"] = True  # idle GC reclaims it
                st["touched"] = time.monotonic() - 0.0
                self._stream_cv.notify_all()

    def _gc_idle_streams(self) -> None:
        """Abandoned finished streams: drop state after cfg.stream_idle_gc_s
        (their sealed items remain normal ref-counted objects; the
        submitting client's holds release through the usual paths)."""
        ttl = cfg.stream_idle_gc_s
        now = time.monotonic()
        undelivered: List[str] = []
        with self._stream_cv:
            dead = [
                tid
                for tid, st in self._streams.items()
                if st["done"] and now - st["touched"] > ttl
            ]
            for tid in dead:
                st = self._streams.pop(tid)
                self._tombstone_stream(tid)
                undelivered.extend(st["items"][st["delivered"]:])
        if undelivered:
            # never-delivered items have no holder (delivery is what
            # registers the consumer); mark tracked so the normal free
            # path reclaims them
            with self._lock:
                for oid in undelivered:
                    e = self._objects.get(oid)
                    if e is not None:
                        e.tracked = True
            self._maybe_free_many(undelivered)

    def _seal_error_ids(
        self,
        object_ids: List[str],
        exc: BaseException,
        keep_for_owner: bool = False,
    ) -> None:
        """Seal error values. ``keep_for_owner`` is the owner-death mode:
        already-produced values win over the error (the reap only fails
        UNproduced objects) and the sealed error entry is made GC-exempt
        so the typed OwnerDiedError outlives the dead owner's holder drop
        (bounded: one small pickled exception per unproduced object)."""
        blob = pickle.dumps(exc)
        with self._cond:
            for oid in object_ids:
                if oid in self._freed:
                    continue
                e = self._objects.setdefault(oid, _ObjEntry())
                if keep_for_owner:
                    if e.event.is_set() and e.error is None:
                        continue  # produced before the owner died
                    e.tracked = False
                e.error = blob
                e.event.set()
                # a failed rebuild ends the reconstruction attempt (no
                # success metric)
                self._reconstructing.pop(oid, None)
            self._cond.notify_all()
        if not keep_for_owner:
            self._maybe_free_many(object_ids)

    def _h_put_object(self, req: dict) -> dict:
        """Driver put: small values inline at the head; large ones are
        forwarded into a node's shared-memory store."""
        object_id, data = req["object_id"], req["data"]
        e = self._entry(object_id)
        holder = req.get("holder")
        with self._lock:
            # owner registration is once-only: an owner-held direct result
            # uploaded here may race a worker's fallback seal (push timed
            # out but actually delivered) — counting the owner twice would
            # leak the object forever
            if holder and not e.owner_registered:
                e.owner_registered = True
                self._add_holder(object_id, holder)
            for inner in req.get("contained_ids", ()):
                if inner not in e.contained:
                    e.contained.append(inner)
                    self._pin(inner)
        if len(data) <= INLINE_OBJECT_MAX:
            e.inline = data
            e.size = len(data)
            e.event.set()
            return {"where": "inline"}
        with self._lock:
            targets = [
                (nid, self._clients[nid])
                for nid, n in self.nodes.items()
                if n.alive
            ]
        for nid, client in targets:
            try:
                client.call(
                    "StoreObject", {"object_id": object_id, "data": data}
                )
                e.locations.add(nid)
                e.size = len(data)
                e.event.set()
                return {"where": nid}
            except RpcError:
                continue
        # no live nodes: keep it inline regardless of size
        e.inline = data
        e.size = len(data)
        e.event.set()
        return {"where": "inline"}

    def _freed_reply(self, object_id: str) -> dict:
        from ray_tpu.core.object_store import ObjectLostError

        return {
            "status": "error",
            "error": pickle.dumps(
                ObjectLostError(
                    f"object {object_id} was freed (all references "
                    "dropped or explicitly freed)"
                )
            ),
        }

    def _sealed_reply(self, e: _ObjEntry) -> dict:
        """Reply for a sealed entry. Caller holds self._lock."""
        if e.error is not None:
            return {"status": "error", "error": e.error}
        if e.inline is not None:
            return {"status": "inline", "data": e.inline}
        locs = [
            (nid, self.nodes[nid].address)
            for nid in e.locations
            if nid in self.nodes and self.nodes[nid].alive
        ]
        if not locs:
            return {"status": "pending"}  # recovery in progress
        return {"status": "located", "locations": locs}

    def _h_locate_objects(self, req: dict) -> Dict[str, List[str]]:
        """Non-blocking batched location lookup from the object directory
        (ray.experimental.get_object_locations analog) — locality-ranked
        dispatch in the Data actor pools rides this."""
        out: Dict[str, List[str]] = {}
        with self._lock:
            for oid in req["object_ids"]:
                e = self._objects.get(oid)
                out[oid] = sorted(e.locations) if e is not None else []
        return out

    def _h_object_sizes(self, req: dict) -> Dict[str, int]:
        """Sealed sizes from the directory (0 = unknown/unsealed); the
        Data executor samples these to calibrate its byte budget."""
        out: Dict[str, int] = {}
        with self._lock:
            for oid in req["object_ids"]:
                e = self._objects.get(oid)
                out[oid] = int(e.size) if e is not None else 0
        return out

    def _h_wait_object(self, req: dict) -> dict:
        """Long-poll for availability (pubsub long-poll analog,
        src/ray/pubsub/)."""
        if req["object_id"] in self._freed:
            return self._freed_reply(req["object_id"])
        e = self._entry(req["object_id"])
        t = req.get("timeout")
        timeout = min(2.0 if t is None else t, 10.0)
        if not e.event.wait(timeout):
            return {"status": "pending"}
        with self._lock:
            return self._sealed_reply(e)

    def _h_wait_object_batch(self, req: dict) -> List[dict]:
        """Batched long-poll: resolve many object ids in one RPC (the
        client's list-get path — one message instead of one per ref,
        matching the reference's batched plasma Get)."""
        ids = req["object_ids"]
        t = req.get("timeout")
        deadline = time.monotonic() + min(2.0 if t is None else t, 10.0)
        replies: Dict[str, dict] = {}
        with self._cond:
            while True:
                for oid in ids:
                    if oid in replies and replies[oid]["status"] != "pending":
                        continue
                    if oid in self._freed:
                        replies[oid] = self._freed_reply(oid)
                        continue
                    e = self._objects.setdefault(oid, _ObjEntry())
                    if e.event.is_set():
                        replies[oid] = self._sealed_reply(e)
                    else:
                        replies[oid] = {"status": "pending"}
                unresolved = sum(
                    1 for r in replies.values() if r["status"] == "pending"
                )
                now = time.monotonic()
                # ray.wait semantics: return as soon as num_returns distinct
                # ids resolved (default: all of them)
                want = req.get("num_returns") or len(set(ids))
                if len(set(ids)) - unresolved >= want or now >= deadline:
                    break
                # seals notify _cond (_apply_seals), so this wakes promptly
                self._cond.wait(timeout=min(0.25, deadline - now))
        return [replies[oid] for oid in ids]

    def _h_free_objects(self, req: dict) -> None:
        """Manual force-free (internal_api.free analog): zero the holder
        counts and let the normal free path cascade (contained pins,
        lineage release, per-node deletes)."""
        ids = req["object_ids"]
        with self._lock:
            for oid in ids:
                e = self._objects.get(oid)
                if e is None:
                    continue
                for holder in list(e.holders):
                    hx = self._holder_hexes.get(holder)
                    if hx is not None:
                        hx.discard(oid)
                e.holders.clear()
                e.pins = 0
                # an explicit free overrides the untracked-entry GC
                # exemption (entries whose refcount state predates a head
                # restart are still force-freeable)
                e.tracked = True
        self._maybe_free_many(ids)

    # ------------------------------------------------------------------
    # distributed refcounting (reference_counter.h:44 analog; centralized
    # at the head instead of the reference's per-owner borrow protocol)
    # ------------------------------------------------------------------
    def _add_holder(self, oid: str, holder: str) -> None:
        """Count one hold of ``oid`` by process ``holder``. Caller holds
        self._lock."""
        e = self._objects.setdefault(oid, _ObjEntry())
        e.holders[holder] = e.holders.get(holder, 0) + 1
        e.tracked = True
        self._holder_hexes.setdefault(holder, set()).add(oid)

    def _pin(self, oid: str) -> None:
        """Pin ``oid`` (lease arg / containing object). Caller holds
        self._lock."""
        e = self._objects.setdefault(oid, _ObjEntry())
        e.pins += 1
        e.tracked = True

    def _h_ref_update(self, req: dict, src: str = "batch") -> None:
        """Client/worker holder-count deltas: ``increfs`` are synchronous
        borrow registrations (sent while the borrowed id is still pinned by
        its outer object or lease), ``decrefs`` are 1→0 instance-count
        releases from a process."""
        holder = req["holder"]
        to_check: List[str] = []
        with self._lock:
            for oid in req.get("increfs", ()):
                if oid in self._freed:
                    continue
                self._add_holder(oid, holder)
            for oid in req.get("decrefs", ()):
                logger.debug("decref %s by %s via %s", oid[:8], holder, src)
                if oid in self._freed:
                    continue
                # a decref can overtake its matching registration across
                # channels (worker decref via agent vs pipelined lease):
                # record the negative so the late registration nets to zero
                e = self._objects.setdefault(oid, _ObjEntry())
                c = e.holders.get(holder, 0) - 1
                if c == 0:
                    e.holders.pop(holder, None)
                else:
                    e.holders[holder] = c
                hx = self._holder_hexes.get(holder)
                if hx is not None:
                    hx.discard(oid)
                to_check.append(oid)
        self._maybe_free_many(to_check)

    def _register_return_holder(self, spec: LeaseRequest) -> None:
        holder = spec.client_id
        with self._lock:
            for oid in spec.return_ids:
                e = self._objects.setdefault(oid, _ObjEntry())
                if e.error is not None and spec.attempt > 0:
                    # owner-side lineage resubmission of a LOST object:
                    # the stale loss error must not shadow the rebuild —
                    # getters park until the re-seal lands
                    e.error = None
                    e.event.clear()
                e.creating_lease = spec.task_id
                e.tracked = True
                if holder and not e.owner_registered:
                    logger.debug("register %s holder %s", oid[:8], holder)
                    e.owner_registered = True
                    self._add_holder(oid, holder)
            if spec.return_ids:
                self._lease_live_returns[spec.task_id] = len(spec.return_ids)
            if spec.arg_ids:
                self._lease_arg_pins[spec.task_id] = list(spec.arg_ids)
                for oid in spec.arg_ids:
                    self._pin(oid)

    def _release_lease_pins(self, task_id: str) -> None:
        """The lease finished (or failed for good): its args no longer need
        to outlive it (LeaseDependencyManager unpin analog)."""
        with self._lock:
            args = self._lease_arg_pins.pop(task_id, None)
            if not args:
                return
            for oid in args:
                e = self._objects.get(oid)
                if e is not None:
                    e.pins -= 1
        self._maybe_free_many(args)

    def _apply_borrows(self, borrows: List[dict]) -> None:
        """A worker finished a task still holding some of its args (stored
        them in actor state): transfer the lease pin into a holder count
        before the pin is released."""
        with self._lock:
            for b in borrows:
                holder = b["holder"]
                for oid in b.get("object_ids", ()):
                    if oid in self._freed:
                        continue
                    self._add_holder(oid, holder)

    def _drop_holder(self, holder: str) -> None:
        """A process died: forget every count it held."""
        with self._lock:
            hexes = list(self._holder_hexes.pop(holder, ()))
            for oid in hexes:
                e = self._objects.get(oid)
                if e is not None:
                    e.holders.pop(holder, None)
        self._maybe_free_many(hexes)

    def _maybe_free_many(self, oids) -> None:
        """Free every listed object whose counts/pins are exhausted, then
        cascade through contained refs and lineage releases."""
        work = list(oids or ())
        deletes: Dict[str, List[str]] = {}  # node -> object ids
        freed_leases: List[str] = []
        with self._lock:
            while work:
                oid = work.pop()
                e = self._objects.get(oid)
                if (
                    e is None
                    or not e.tracked
                    or not e.event.is_set()
                    or e.pins > 0
                    or any(c > 0 for c in e.holders.values())
                ):
                    continue
                logger.debug(
                    "GC free %s holders=%s pins=%s", oid[:8], e.holders, e.pins
                )
                del self._objects[oid]
                self._freed.add(oid)
                for nid in e.locations:
                    deletes.setdefault(nid, []).append(oid)
                for inner in e.contained:
                    ie = self._objects.get(inner)
                    if ie is not None:
                        ie.pins -= 1
                        work.append(inner)
                lid = e.creating_lease
                if lid is not None and lid in self._lease_live_returns:
                    self._lease_live_returns[lid] -= 1
                    if self._lease_live_returns[lid] <= 0:
                        del self._lease_live_returns[lid]
                        freed_leases.append(lid)
            # lineage release: all outputs of these leases are gone — the
            # spec (and the arg refs its payload pins) can go too
            for lid in freed_leases:
                self._leases.pop(lid, None)
            if freed_leases:
                self._persist_dirty = True  # lineage shrank
            clients = {
                nid: self._clients.get(nid)
                for nid in deletes
                if self.nodes.get(nid) is not None
            }
        for nid, ids in deletes.items():
            client = clients.get(nid)
            if client is not None:
                self._dispatch_pool.submit(
                    _best_effort,
                    client.call,
                    "DeleteObjects",
                    {"object_ids": ids},
                )

    # ------------------------------------------------------------------
    # lease intake + the batched scheduler
    # ------------------------------------------------------------------
    def _h_submit_lease(self, spec: LeaseRequest) -> dict:
        # reconstruction-class resubmissions (attempt > 0: owner-side
        # lineage rebuilds, at-least-once redeliveries) dedup by task_id —
        # one rebuild re-seals every getter's wait; first submissions
        # (the hot path) skip the scan entirely
        if spec.attempt > 0:
            with self._cond:
                if spec.task_id in self._in_flight or any(
                    s.task_id == spec.task_id
                    for q in (
                        self._pending,
                        self._scheduling_batch,
                        # dispatched-but-uncompleted pipelined rounds hold
                        # specs no other queue shows
                        *self._deferred_rounds.values(),
                    )
                    for s in q
                ):
                    return {"queued": True, "dedup": True}
        self._register_return_holder(spec)
        if spec.streaming:
            # the stream exists from submission: a consumer's WaitStream
            # can land before the first item (or even before dispatch)
            with self._stream_cv:
                self._stream_state(spec.task_id)
                self._stream_cv.notify_all()
        with self._cond:
            self._leases[spec.task_id] = spec
            self.metrics["leases_submitted"] += 1
            self._pending.append(spec)
            self._cond.notify_all()
        self.events.record(
            spec.task_id, spec.name, "SUBMITTED", **_trace_args(spec)
        )
        # lineage rides the debounced snapshot (no WAL: too hot per-lease)
        if spec.kind == "task" and spec.return_ids:
            self._mark_hot_dirty()
        return {"queued": True}

    def _h_client_batch(self, items: List[tuple]) -> None:
        """Pipelined client control stream: ordered lease submissions,
        refcount updates, and actor create/kill coalesced into one RPC
        (see client._PipelinedSender). Actor churn riding the pipeline is
        the control-plane fast path: the driver never blocks a creation
        behind a loaded head's reply, and create→kill order is preserved
        by the single queue."""
        for kind, payload in items:
            if kind == "lease":
                self._h_submit_lease(payload)
            elif kind == "ref":
                self._h_ref_update(payload)
            elif kind == "create_actor":
                # swallowed, not re-raised: the sender retries a failed
                # ClientBatch forever, so one poison creation must not
                # wedge every lease queued behind it (unnamed creations
                # have no name-taken failure mode; anything else here is
                # a bug surfaced via head_dropped_callbacks)
                _best_effort(self._h_create_actor, payload)
            elif kind == "kill_actor":
                _best_effort(self._h_kill_actor, payload)
            elif kind == "lease_renew":
                _best_effort(self._h_lease_renew, payload)
            elif kind == "lease_return":
                _best_effort(self._h_lease_return, payload)
            elif kind == "owner_beat":
                _best_effort(self._h_owner_beat, payload)

    # ------------------------------------------------------------------
    # task leases (lease-cached direct dispatch): the head schedules
    # LEASE GRANTS through the same batched kernel that places tasks —
    # a worker_lease spec rides the pending queue, the kernel picks its
    # node, the agent allocates the shape + pins a worker, and the
    # activation report closes the loop back to the waiting owner. From
    # then on the owner streams same-shape tasks straight to the leased
    # worker; the head only sees renewals, the eventual return, and the
    # batched seal reports that keep its object directory authoritative.
    # ------------------------------------------------------------------
    def _h_grant_task_lease(self, req: dict) -> dict:
        """Owner requests a cacheable worker lease for a task shape.
        Long-polls until the grant activates (or the window closes — the
        owner keeps using the per-task head path and may retry)."""
        if not cfg.task_leases:
            return {"granted": False, "reason": "task leases disabled"}
        # bound concurrent grant long-polls: each occupies an RPC server
        # thread for up to its window, and a burst of cold shapes against
        # a full cluster must not starve ReportSeals/ClientBatch/renewal
        # traffic out of the pool — rejected grants fail fast and the
        # owner's cooldown retries later
        if not self._grant_gate.acquire(blocking=False):
            return {"granted": False, "reason": "grant queue full"}
        try:
            return self._grant_task_lease_inner(req)
        finally:
            self._grant_gate.release()

    def _grant_task_lease_inner(self, req: dict) -> dict:
        resources = dict(req.get("resources") or {})
        lease_id = new_id()
        ttl = cfg.task_lease_ttl_s
        spec = LeaseRequest(
            task_id=lease_id,
            name=f"worker_lease:{(req.get('fn_id') or '')[:8]}",
            payload=b"",
            return_ids=[],
            resources=resources,
            kind="worker_lease",
            max_retries=0,
            client_id=req.get("client_id", ""),
        )
        with self._cond:
            self._task_leases[lease_id] = {
                "lease_id": lease_id,
                "state": "granting",
                "resources": resources,
                "client_id": spec.client_id,
                "fn_id": req.get("fn_id", ""),
                "node_id": None,
                "worker_address": None,
                "worker_id": None,
                "accel_env": None,
                "expires_at": time.monotonic() + max(3.0 * ttl, 15.0),
                "abandoned": False,
            }
            self._leases[lease_id] = spec
            self._pending.append(spec)
            self._cond.notify_all()
        deadline = time.monotonic() + min(
            float(req.get("timeout") or 10.0), 30.0
        )
        with self._cond:
            while True:
                e = self._task_leases.get(lease_id)
                if e is None:
                    return {
                        "granted": False,
                        "reason": "grant failed (no worker available)",
                    }
                if e["state"] == "active":
                    return {
                        "granted": True,
                        "lease_id": lease_id,
                        "node_id": e["node_id"],
                        "worker_address": e["worker_address"],
                        "accel_env": e["accel_env"],
                        "max_inflight": int(cfg.task_lease_max_inflight),
                        "ttl_s": float(ttl),
                    }
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # still queued/in flight: mark abandoned — the
                    # dispatch-time filter drops it if unplaced, and a
                    # late activation is released straight back
                    e["abandoned"] = True
                    self._cancelled_leases.add(lease_id)
                    return {
                        "granted": False,
                        "reason": "grant timed out (no capacity)",
                    }
                self._cond.wait(remaining)

    def _apply_task_lease_reports(self, reports: List[dict]) -> None:
        """Agent-side grant activations and losses (ReportSeals
        ``task_leases`` entries)."""
        for tl in reports:
            lease_id = tl["lease_id"]
            release_node = None
            with self._cond:
                e = self._task_leases.get(lease_id)
                if not tl.get("ok"):
                    # grant failed agent-side, or the leased worker died
                    if e is not None:
                        was_active = e["state"] == "active"
                        self._drop_task_lease_locked(lease_id)
                        if was_active or tl.get("lost"):
                            self.metrics["task_leases_revoked"] += 1
                            TASK_LEASE_REVOKED.inc()
                    self._cond.notify_all()
                elif e is None or e.get("abandoned"):
                    # nobody is waiting for this grant anymore (owner
                    # timed out / head restarted): release it right back
                    self._drop_task_lease_locked(lease_id)
                    release_node = tl.get("node_id")
                else:
                    e.update(
                        state="active",
                        node_id=tl.get("node_id"),
                        worker_address=tl.get("worker_address"),
                        worker_id=tl.get("worker_id"),
                        accel_env=tl.get("accel_env"),
                        expires_at=time.monotonic()
                        + max(3.0 * cfg.task_lease_ttl_s, 15.0),
                        abandoned=False,
                    )
                    self.metrics["task_leases_granted"] += 1
                    TASK_LEASE_GRANTED.inc()
                    self._wal(("task_lease", self._lease_snapshot_row(e)))
                    self._cond.notify_all()
            self._wal_flush()
            if release_node is not None:
                self._agent_return_lease(release_node, lease_id)

    @staticmethod
    def _lease_snapshot_row(e: dict) -> dict:
        """Durable slice of a lease row (monotonic expiry rebased on
        load)."""
        row = {
            k: e[k]
            for k in (
                "lease_id",
                "resources",
                "client_id",
                "fn_id",
                "node_id",
                "worker_address",
                "worker_id",
                "accel_env",
            )
        }
        row["ttl_remaining_s"] = max(
            0.0, e["expires_at"] - time.monotonic()
        )
        return row

    def _drop_task_lease_locked(self, lease_id: str) -> Optional[dict]:
        """Forget a lease everywhere. Caller holds self._lock."""
        e = self._task_leases.pop(lease_id, None)
        self._in_flight.pop(lease_id, None)
        self._leases.pop(lease_id, None)
        if e is not None:
            self._wal(("task_lease_gone", lease_id))
        return e

    def _agent_return_lease(self, node_id: str, lease_id: str) -> None:
        self._queue_revoke(
            "ReturnWorkerLease", node_id, {"lease_id": lease_id}
        )

    # ------------------------------------------------------------------
    # durable revocation fan-out: every agent-bound revoke (worker-lease
    # returns, peer-link revokes) is WAL-recorded BEFORE the send and
    # cleared only after delivery. A dying leader's best-effort sends
    # used to race the standby's rebuild; now the successor re-drives
    # whatever is still pending — the receivers are idempotent, so a
    # duplicate delivery is a no-op.
    # ------------------------------------------------------------------
    def _queue_revoke(self, method: str, node_id: str, payload: dict) -> None:
        rid = new_id()
        row = {
            "revoke_id": rid,
            "method": method,
            "node_id": node_id,
            "payload": payload,
            "queued_at": time.time(),
            "attempted_at": time.monotonic(),
        }
        with self._lock:
            if self._fenced:
                return  # deposed: the new leader drives its own revokes
            self._pending_revokes[rid] = row
            self._wal(("revoke_pending", dict(row)))
        self._wal_flush()
        try:
            self._dispatch_pool.submit(_best_effort, self._drive_revoke, rid)
        except RuntimeError:
            pass  # pool closed (shutdown); the record re-drives elsewhere

    def _drive_revoke(self, rid: str) -> None:
        with self._lock:
            row = self._pending_revokes.get(rid)
            if row is None or self._fenced:
                return
            client = self._clients.get(row["node_id"])
        if client is None:
            return  # node not (re-)registered yet: re-driven when it is
        try:
            # closed revoke-kind set, dispatched through literal call
            # sites (the static rpc-table check sees them; a new kind
            # must be added here deliberately)
            if row["method"] == "ReturnWorkerLease":
                client.call(
                    "ReturnWorkerLease",
                    dict(row["payload"]),
                    timeout=10.0,
                    retries=2,
                )
            elif row["method"] == "RevokePeerLink":
                client.call(
                    "RevokePeerLink",
                    dict(row["payload"]),
                    timeout=10.0,
                    retries=2,
                )
            else:
                raise ValueError(
                    f"unknown revoke kind {row['method']!r}"
                )
        except Exception:  # noqa: BLE001 - stays pending; re-driven later
            HEAD_DROPPED_CALLBACKS.inc(
                labels={"callable": f"revoke:{row['method']}"}
            )
            logger.debug(
                "revoke %s to %s not delivered; re-driving later",
                row["method"],
                row["node_id"],
                exc_info=True,
            )
            return
        with self._lock:
            if self._pending_revokes.pop(rid, None) is not None:
                self._wal(("revoke_done", rid))
        self._wal_flush()

    def _redrive_revokes(self, node_id: Optional[str] = None) -> None:
        """Re-send pending revokes (all, or one re-registering node's) —
        the promotion/restart path that replaces trusting a dead
        leader's last best-effort breaths."""
        with self._lock:
            rids = [
                rid
                for rid, row in self._pending_revokes.items()
                if node_id is None or row["node_id"] == node_id
            ]
        for rid in rids:
            try:
                self._dispatch_pool.submit(
                    _best_effort, self._drive_revoke, rid
                )
            except RuntimeError:
                return

    def _expire_pending_revokes(self) -> None:
        """Health-loop sweep over undelivered revokes: rows whose target
        node is LIVE re-drive periodically (a one-off send failure to a
        healthy agent must not pin its worker forever — RegisterNode is
        not the only re-drive trigger); rows whose node is gone past the
        redrive TTL can never deliver and drop (the agent-side resource
        died with the node anyway)."""
        ttl = float(cfg.revoke_redrive_ttl_s)
        now = time.time()
        now_m = time.monotonic()
        victims = []
        retry = []
        with self._lock:
            for rid, row in self._pending_revokes.items():
                node = self.nodes.get(row["node_id"])
                alive = node is not None and node.alive
                if alive:
                    if now_m - row.get("attempted_at", 0.0) > 5.0:
                        row["attempted_at"] = now_m
                        retry.append(rid)
                elif now - row.get("queued_at", now) > ttl:
                    victims.append(rid)
            for rid in victims:
                self._pending_revokes.pop(rid, None)
                self._wal(("revoke_done", rid))
        if victims:
            self._wal_flush()
        for rid in retry:
            try:
                self._dispatch_pool.submit(
                    _best_effort, self._drive_revoke, rid
                )
            except RuntimeError:
                return

    def _h_lease_renew(self, req: dict) -> None:
        """Owner heartbeat while its queue is non-empty (ClientBatch
        ``lease_renew``): pushes the expiry out so the dead-owner sweep
        never revokes a flowing lease."""
        horizon = time.monotonic() + max(3.0 * cfg.task_lease_ttl_s, 15.0)
        with self._lock:
            for lid in req.get("lease_ids", ()):
                e = self._task_leases.get(lid)
                if e is not None:
                    e["expires_at"] = horizon

    def _h_lease_return(self, req: dict) -> None:
        """Owner returned a lease (queue drain / idle TTL / shutdown)."""
        lease_id = req["lease_id"]
        with self._cond:
            e = self._drop_task_lease_locked(lease_id)
            if e is not None:
                self.metrics["task_leases_returned"] += 1
                TASK_LEASE_RETURNED.inc()
            self._cond.notify_all()
        self._wal_flush()
        node_id = (e or {}).get("node_id") or req.get("node_id")
        if node_id:
            # forward even when the table missed it (unpersisted head
            # restart): the agent-side release is what unpins the worker
            self._agent_return_lease(node_id, lease_id)

    def _expire_task_leases(self) -> None:
        """Dead-owner safety net: revoke leases not renewed within
        3x TTL (floored at 15s — renewals ride the pipelined ClientBatch
        and may lag under load; revoking a healthy flowing lease costs a
        spill storm). A live owner renews while busy, returns on idle."""
        now = time.monotonic()
        with self._lock:
            victims = [
                (lid, e.get("node_id"))
                for lid, e in self._task_leases.items()
                if now > e["expires_at"]
            ]
        for lid, node_id in victims:
            logger.info("task lease %s expired; revoking", lid[:8])
            with self._cond:
                if self._drop_task_lease_locked(lid) is None:
                    continue
                self.metrics["task_leases_revoked"] += 1
                TASK_LEASE_REVOKED.inc()
                self._cond.notify_all()
            self._wal_flush()
            if node_id:
                self._agent_return_lease(node_id, lid)

    # ------------------------------------------------------------------
    # peer data links (cross-node transport, transport.py): the task-
    # lease pattern applied to connections — the head grants a peer link
    # ONCE per (src, dst) pair (endpoint + auth token + epoch in the
    # grant), then steady-state transfers make zero head RPCs. Links
    # renew while hot via piggybacked agent reports, are reclaimed on
    # the requester's idle TTL (ReturnPeerLink), expire on a missed-
    # renewal sweep, and are revoked when either endpoint node dies.
    # ------------------------------------------------------------------
    def _h_grant_peer_link(self, req: dict) -> dict:
        if not cfg.native_net:
            return {"granted": False, "reason": "native net disabled"}
        src = req.get("src_node", "")
        dst = req["dst_node"]
        ttl = cfg.peer_link_ttl_s
        with self._lock:
            node = self.nodes.get(dst)
            if (
                node is None
                or not node.alive
                or not getattr(node, "data_endpoint", "")
            ):
                return {
                    "granted": False,
                    "reason": f"node {dst} has no live data endpoint",
                }
            lid = self._peer_links_by_pair.get((src, dst))
            e = self._peer_links.get(lid) if lid else None
            if e is None:
                e = {
                    "link_id": new_id(),
                    "src": src,
                    "dst": dst,
                    "endpoint": node.data_endpoint,
                    "granted_at": time.time(),
                    "expires_at": time.monotonic() + max(3.0 * ttl, 15.0),
                }
                self._peer_links[e["link_id"]] = e
                self._peer_links_by_pair[(src, dst)] = e["link_id"]
                self.metrics["peer_links_granted"] += 1
                PEER_CONN_GRANTED.inc()
                self._wal(("peer_link", self._peer_link_row(e)))
            else:
                # same pair re-granting (requester restarted or dropped
                # its cache): refresh the existing row, don't duplicate
                e["endpoint"] = node.data_endpoint
                e["expires_at"] = time.monotonic() + max(3.0 * ttl, 15.0)
            reply = {
                "granted": True,
                "link_id": e["link_id"],
                "node_id": dst,
                "endpoint": node.data_endpoint,
                # the token travels only in the grant reply (never the
                # WAL/snapshot — parity with the on-disk endpoint file)
                "token": getattr(node, "net_token", ""),
                "epoch": self.cluster_epoch,
                "ttl_s": float(ttl),
            }
        self._wal_flush()
        return reply

    @staticmethod
    def _peer_link_row(e: dict) -> dict:
        row = {
            k: e[k] for k in ("link_id", "src", "dst", "endpoint", "granted_at")
        }
        row["ttl_remaining_s"] = max(0.0, e["expires_at"] - time.monotonic())
        return row

    def _drop_peer_link_locked(
        self, link_id: str, revoked: bool = True
    ) -> Optional[dict]:
        e = self._peer_links.pop(link_id, None)
        if e is None:
            return None
        pair = (e["src"], e["dst"])
        if self._peer_links_by_pair.get(pair) == link_id:
            del self._peer_links_by_pair[pair]
        self._wal(("peer_link_gone", link_id))
        if revoked:
            self.metrics["peer_links_revoked"] += 1
            PEER_CONN_REVOKED.inc()
        return e

    def _h_return_peer_link(self, req: dict) -> None:
        """Requester reclaimed an idle link (idle TTL / shutdown)."""
        with self._lock:
            self._drop_peer_link_locked(req["link_id"], revoked=False)
        self._wal_flush()

    def _renew_peer_links(self, link_ids) -> None:
        """Piggybacked renewals from agent reports (renew-while-hot)."""
        horizon = time.monotonic() + max(3.0 * cfg.peer_link_ttl_s, 15.0)
        with self._lock:
            for lid in link_ids:
                e = self._peer_links.get(lid)
                if e is not None:
                    e["expires_at"] = horizon

    def _expire_peer_links(self) -> None:
        """Dead-holder safety net: drop links not renewed within 3x TTL
        (a crashed requester can't ReturnPeerLink). No agent callout —
        the requester side re-grants on next use, and the serving side
        authenticates per handshake, not per table row."""
        now = time.monotonic()
        with self._lock:
            victims = [
                lid
                for lid, e in self._peer_links.items()
                if now > e["expires_at"]
            ]
            for lid in victims:
                self._drop_peer_link_locked(lid)
        if victims:
            self._wal_flush()

    def _revoke_node_peer_links(self, node_id: str) -> None:
        """Node death: revoke every link touching it, and tell surviving
        REQUESTERS to drop their cached grants promptly (best-effort —
        a stale cached link also dies on its next handshake, because the
        dead node's token/endpoint are gone)."""
        with self._lock:
            victims = [
                dict(e)
                for e in self._peer_links.values()
                if node_id in (e["src"], e["dst"])
            ]
            for e in victims:
                self._drop_peer_link_locked(e["link_id"])
        if not victims:
            return
        self._wal_flush()
        for e in victims:
            if e["dst"] != node_id:
                continue  # only the requester side holds a cache
            # WAL-backed fan-out: a leader dying mid-revoke leaves the
            # record for its successor to re-drive (pool-closed races
            # are absorbed inside _queue_revoke)
            self._queue_revoke(
                "RevokePeerLink",
                e["src"],
                {"link_id": e["link_id"], "node_id": e["dst"]},
            )

    @property
    def device_state(self):
        """Lazy DeviceSchedulerState with bring-up timeout: JAX backend init
        happens on the first scheduling round (never at construction), and a
        wedged accelerator transport degrades to the host golden model
        instead of freezing the scheduler (scheduler/device.py
        LazyDeviceState)."""
        return self._lazy_device.get()

    def _scheduler_loop(self) -> None:
        while True:
            with self._cond:
                while (
                    not self._pending
                    and not (self._pending_pgs and self._pgs_dirty)
                    and not self._shutdown
                    and not self._fenced
                ):
                    self._cond.wait(timeout=0.5)
                    # Retry parked work only when the view actually moved,
                    # so truly-infeasible specs don't spin the kernel at
                    # 2 Hz.
                    self._maybe_unpark_locked()
                if self._shutdown or self._fenced:
                    # fenced: a deposed leader must not grant anything —
                    # the new leader owns every queued spec's fate (its
                    # owners re-hello and resubmit there)
                    return
                # parked work also retries while NEW submissions keep the
                # queue hot — without this, a steady submit stream starves
                # every parked spec (the wait loop above never runs)
                self._maybe_unpark_locked()
                batch = self._pop_fair_batch()
                # demand visibility: the popped batch is mid-schedule, not
                # gone — the autoscaler must still see it (the first round
                # can stall for seconds in XLA backend bring-up)
                self._scheduling_batch = batch
            t_round = time.perf_counter()
            deferred = False
            try:
                self._try_schedule_pgs()
                if batch:
                    deferred = bool(self._schedule_batch(batch))
            except Exception:  # pragma: no cover - scheduler must survive
                logger.exception("scheduler round failed; requeueing")
                with self._cond:
                    self._pending.extend(batch)
            finally:
                # pipelined rounds observe dispatch→grant latency from the
                # completion thread instead (the loop only dispatched)
                if batch and not deferred:
                    SCHED_ROUND_MS.observe(
                        (time.perf_counter() - t_round) * 1e3
                    )
                self._scheduling_batch = []
            time.sleep(SCHED_TICK_S)

    def _maybe_unpark_locked(self) -> None:
        """Rate-limited, change-gated entry to ``_unpark_grantable``:
        completions bump the change counter continuously under load;
        re-routing parked specs each 2ms tick multiplies per-spec Python
        work ~10x for no placement gain. Caller holds ``self._cond``."""
        if self._infeasible and (
            (
                self.view.change_counter != self._parked_at_change
                and time.monotonic() - self._last_park_retry > 0.02
            )
            # liveness fallback: capacity can free without a view change
            # (PG bundle books are bundle-local) — retry parked work at
            # 1 Hz regardless, bounded by the per-shape cap
            or time.monotonic() - self._last_park_retry > 1.0
        ):
            self._parked_at_change = self.view.change_counter
            self._last_park_retry = time.monotonic()
            self._unpark_grantable()

    def _unpark_grantable(self) -> None:
        """Move parked specs back to pending, capped per resource shape at
        what the current view could actually grant.

        Re-feeding the ENTIRE parked queue on every capacity-freeing event
        is O(parked²) aggregate scheduling work under a deep backlog (5k
        parked specs × ~40 unpark events re-scores ~200k placements to
        grant 5k) — exactly the storm the reference avoids by leaving
        unschedulable scheduling classes parked until resources change and
        retrying them per-class (cluster_lease_manager.cc:298
        TryScheduleInfeasibleLease + local_lease_manager.h per-class
        backoff). Here: per shape, estimate grantable slots from the live
        avail arrays and unpark only that many (+slack for estimate
        error); the remainder stays parked for the next change event.
        Constrained specs (strategy / PG / target-node routed) don't fit
        the shape-capacity math and unpark slack-at-a-time. Caller holds
        ``self._cond``."""
        from ray_tpu.scheduler.unpark import (
            UNPARK_SLACK,
            select_unparkable_resilient,
        )

        parked = self._infeasible
        device_state = self._lazy_device._result
        if not parked:
            self._reconcile_ring(device_state)
            return
        if len(parked) <= UNPARK_SLACK:
            # below the slack there is nothing to cap: skip the view
            # lock + array copies entirely (steady-state common case)
            self._pending.extend(parked)
            self._infeasible = []
            self._reconcile_ring(device_state)
            return
        keep_ring: List[LeaseRequest] = []
        rest = parked
        if device_state is not None and device_state.ring_slots > 0:
            # ring-resident shapes place straight off the device (no
            # demand re-upload, no trip back through the round path);
            # the remainder below is constrained / unknown-resource /
            # ring-overflow work
            try:
                rest, keep_ring = self._unpark_via_ring(device_state, parked)
            except Exception:  # noqa: BLE001 - scheduler must survive
                # this runs OUTSIDE the loop's _schedule_batch guard: an
                # XLA error here must not kill the scheduler thread. No
                # grants were sent (the kernel/readback precedes every
                # side effect except harmless ring parks), but the ring
                # round may have deducted on device — purge via full
                # re-sync and retry everything through the host path.
                logger.exception("ring unpark failed; host fallback")
                device_state.invalidate()
                rest, keep_ring = parked, []
        if not rest:
            self._infeasible = keep_ring
            self._reconcile_ring(device_state)
            return
        slots_fn = None
        if device_state is not None and cfg.sched_unpark_device:
            try:
                with self._lock:
                    device_state.sync(self.view)
                    _, avail, alive = self.view.active_arrays()
                # batched slot estimate over the RESIDENT arrays —
                # avail/alive above are only consulted for the
                # resource-axis width
                slots_fn = device_state.shape_slots
            except Exception:  # noqa: BLE001 - scheduler must survive
                logger.exception("device unpark sync failed; host scan")
                device_state.invalidate()
        if slots_fn is None:
            with self._lock:
                _, a0, al0 = self.view.active_arrays()
                avail = a0.copy()
                alive = al0.copy()
        # grants in flight (worker leases being placed) consume capacity
        # the availability arrays won't show until the agent's next
        # report: count their demand against the slot estimate
        reserved = [
            self._spec_req(
                self._leases.get(lid)
            ).dense(avail.shape[1])
            for lid, e in self._task_leases.items()
            if e["state"] == "granting" and self._leases.get(lid) is not None
        ]
        def _refetch():
            with self._lock:
                _, a0, al0 = self.view.active_arrays()
                return a0.copy(), al0.copy()

        take, keep = select_unparkable_resilient(
            rest,
            avail,
            alive,
            device_state=device_state,
            slots_fn=slots_fn,
            refetch=_refetch,
            is_constrained=lambda s: (
                s.strategy is not None or s.target_node or s.pg_reservation
            ),
            resources_of=lambda s: s.resources,
            request_of=self._spec_req,
            reserved=reserved or None,
            age_of=lambda k: self._shape_wait.get(k, 0),
        )
        self._pending.extend(take)
        self._infeasible = keep + keep_ring
        self._reconcile_ring(device_state)

    def _reconcile_ring(self, device_state) -> None:
        """Drop ring slots whose shape has no parked spec left. Specs
        routinely leave the parked state WITHOUT passing the in-ring
        drain that calls ring_drop (the small-queue fast path and
        select_unparkable's take list above) — without this sweep, 64
        distinct ever-parked shapes would permanently exhaust the ring
        and silently disable it for the life of the process. Caller
        holds self._cond."""
        if device_state is None or not device_state.ring_occupancy():
            return
        still = {_shape_key_of(s) for s in self._infeasible}
        for key in device_state.ring_keys():
            if key not in still:
                device_state.ring_drop(key)

    def _unpark_via_ring(
        self, device_state, parked: List[LeaseRequest]
    ) -> Tuple[List[LeaseRequest], List[LeaseRequest]]:
        """Place ring-eligible parked specs straight from the on-device
        parked-demand ring. Returns (rest, still_parked): specs the ring
        cannot serve (constrained, unknown resource, ring full), and
        ring-eligible specs the cluster had no capacity for. Placed specs
        are granted here (same optimistic-deduction + grant-or-reject
        contract as a kernel round). Caller holds self._cond."""
        with self._lock:
            r = self.view.totals.shape[1]
        ring_q: Dict[tuple, List[LeaseRequest]] = {}
        rest: List[LeaseRequest] = []
        for spec in parked:
            if (
                spec.strategy is not None
                or spec.target_node
                or spec.pg_reservation
            ):
                rest.append(spec)
                continue
            req = self._spec_req(spec)
            if any(c >= r and fp > 0 for c, fp in req.demands.items()):
                rest.append(spec)
                continue
            key = _shape_key_of(spec)
            if (
                device_state.ring_slot_of(key) is None
                and not device_state.ring_park(key, req.dense(r))
            ):
                rest.append(spec)  # ring full: normal unpark path
                continue
            ring_q.setdefault(key, []).append(spec)
        if not ring_q:
            return rest, []
        with self._lock:
            device_state.sync(self.view)
        counts = {
            device_state.ring_slot_of(key): len(q)
            for key, q in ring_q.items()
        }
        starve_rounds = max(1, int(cfg.sched_starve_rounds))
        ages = {
            device_state.ring_slot_of(key): (
                self._shape_wait.get(key, 0) / starve_rounds
            )
            for key in ring_q
        }
        placed, per_node, pre_rows = device_state.ring_schedule(
            counts,
            spread_threshold=self.hybrid_config.spread_threshold,
            ages_by_slot=ages,
        )
        still_parked: List[LeaseRequest] = []
        grants: Dict[str, List[LeaseRequest]] = {}
        nominations: List[Tuple[tuple, int]] = []
        n = per_node.shape[1]
        for key, q in ring_q.items():
            slot = device_state.ring_slot_of(key)
            k = min(int(placed[slot]), len(q))
            if k:
                # per-node placement counts → node row per FIFO rank; the
                # host mirror deducts EXACTLY what the kernel deducted
                # (k × shape), keeping the two copies convergent
                node_rows = np.repeat(np.arange(n), per_node[slot])[:k]
                d = self._spec_req(q[0]).dense(r)
                with self._lock:
                    self.view.subtract_many(
                        node_rows, np.broadcast_to(d, (k, r))
                    )
                    for spec, row in zip(q[:k], node_rows):
                        grants.setdefault(
                            self.view.node_id(int(row)), []
                        ).append(spec)
            still_parked.extend(q[k:])
            if k == len(q):
                device_state.ring_drop(key)  # queue drained: free the slot
                self._shape_wait.pop(key, None)
                self._preempt_cooldown.pop(key, None)
            elif k > 0:
                # class made progress: not starving (see _fan_out_grants)
                self._shape_wait.pop(key, None)
            else:
                # the ring retry IS this shape's scheduling round: age it
                self._shape_wait[key] = self._shape_wait.get(key, 0) + 1
                if int(pre_rows[slot]) >= 0:
                    nominations.append(
                        (key, int(pre_rows[slot]), self._spec_req(q[0]).dense(r))
                    )
        if nominations:
            self._handle_ring_preempt(nominations)
        if grants:
            self.metrics["leases_unparked_ring"] = self.metrics.get(
                "leases_unparked_ring", 0
            ) + sum(len(v) for v in grants.values())
            self._send_grants(grants)
        return rest, still_parked

    def _pop_fair_batch(self) -> List[LeaseRequest]:
        """Take up to MAX_BATCH leases. When the queue overflows one round,
        round-robin across scheduling classes (resource shapes) so a storm
        of one shape cannot monopolize dispatch for rounds on end
        (local_lease_manager.h per-class throttling analog). Caller holds
        self._cond."""
        if self._cancelled_leases:
            drop = self._cancelled_leases
            kept = [s for s in self._pending if s.task_id not in drop]
            for s in self._pending:
                if s.task_id in drop:
                    drop.discard(s.task_id)
            self._pending = deque(kept)
        if len(self._pending) <= MAX_BATCH:
            batch = list(self._pending)
            self._pending.clear()
            return batch
        # bound the rebucketing window: scanning the WHOLE queue per tick
        # would be O(pending) under the head lock during exactly the storm
        # that triggers this branch. Fairness applies within the window;
        # the untouched tail keeps FIFO order.
        window = min(len(self._pending), 4 * MAX_BATCH)
        scanned = [self._pending.popleft() for _ in range(window)]
        by_class: Dict[tuple, deque] = {}
        order: List[tuple] = []
        for spec in scanned:
            # same cached key _round_shapes uses: a spec re-scanned every
            # storm round must not re-sort its resources dict each time
            key = _shape_key_of(spec)
            q = by_class.get(key)
            if q is None:
                q = by_class[key] = deque()
                order.append(key)
            q.append(spec)
        batch: List[LeaseRequest] = []
        while len(batch) < MAX_BATCH:
            progressed = False
            for key in order:
                q = by_class[key]
                if q:
                    batch.append(q.popleft())
                    progressed = True
                    if len(batch) >= MAX_BATCH:
                        break
            if not progressed:
                break
        # window remainder returns to the FRONT (per-class FIFO preserved),
        # ahead of the untouched tail
        for key in reversed(order):
            self._pending.extendleft(reversed(by_class[key]))
        return batch

    def _spec_req(self, spec: LeaseRequest) -> "ResourceRequest":
        """Memoized packed demand: a spec spilled back under contention is
        re-routed many times; packing its (immutable) resources dict once
        removes the dominant per-round Python cost."""
        req = getattr(spec, "_req_cache", None)
        if req is None:
            req = ResourceRequest.from_map(self.vocab, spec.resources)
            spec._req_cache = req
        return req

    def _schedule_batch(self, batch: List[LeaseRequest]) -> bool:
        """Route and place one popped batch. Returns True when the kernel
        half was dispatched into the pipeline (grants fan out from the
        completion thread); False when the round completed inline."""
        self.metrics["sched_rounds"] += 1
        kernel_batch: List[LeaseRequest] = []
        spread_batch: List[LeaseRequest] = []
        for spec in batch:
            routed = self._route_constrained(spec)
            if routed == "kernel":
                kernel_batch.append(spec)
            elif routed == "spread":
                spread_batch.append(spec)
        if spread_batch:
            self._schedule_spread(spread_batch)
        if not kernel_batch:
            return False
        totals = avail = alive = None
        # crossover: tiny rounds pay more in device dispatch than the
        # kernel saves — below the threshold use the host golden model
        # (same math; scheduler/hybrid.py golden tests pin equivalence).
        # Checked BEFORE the device_state property so a tiny round never
        # triggers the lazy XLA backend bring-up it would then discard.
        if len(kernel_batch) < cfg.sched_device_min_batch:
            device_state = None
        else:
            # lazy XLA/backend init happens OUTSIDE the view lock: a slow
            # (or wedged) backend bring-up must stall only the scheduler
            # thread, never every RPC handler that needs the lock
            device_state = self.device_state
        with self._lock:
            n = self.view.num_nodes
            r = self.view.totals.shape[1]
            any_alive = bool(self.view.alive.any())
            if device_state is not None and n > 0:
                device_state.sync(self.view)
            else:
                # snapshot copies for the host reference scheduler: RPC
                # threads mutate the view concurrently (node add/remove,
                # resource reports); rows never shift, so row->node_id stays
                # valid after release.
                t0, a0, al0 = self.view.active_arrays()
                totals, avail, alive = t0.copy(), a0.copy(), al0.copy()
        if n == 0 or not any_alive:
            with self._cond:
                self._infeasible.extend(kernel_batch)
            return False
        (
            specs,
            shape_rows,
            sids,
            infeasible,
            keys,
            ages,
            loc,
        ) = self._round_shapes(kernel_batch, r)
        if infeasible:
            # a demand column past the view's resource axis names a
            # resource no node has ever reported — unplaceable until the
            # cluster changes
            with self._cond:
                self._infeasible.extend(infeasible)
        if not specs:
            return False
        if device_state is not None:
            # the default path: shape-grouped waterfall kernel over the
            # device-resident view (device.py module docstring). Pipelined
            # (cfg.sched_pipeline): dispatch round N+1 while round N's
            # placements are still being read back — the avail chain
            # sequences the rounds on device, and grants fan out from the
            # pipeline's completion thread.
            if cfg.sched_pipeline:
                pending = device_state.schedule_async(
                    spread_threshold=self.hybrid_config.spread_threshold,
                    shapes=(shape_rows, sids),
                    ages=ages,
                    locality=loc,
                )
                sched = (specs, shape_rows, sids, keys, pending, loc)
                pending.ctx = sched
                with self._cond:
                    self._deferred_rounds[id(sched)] = specs
                try:
                    self._ensure_pipeline().submit(pending)
                except Exception:
                    # pipeline stopped (shutdown race) or submit died. The
                    # kernel already dispatched — its deductions sit on the
                    # resident avail with no completion to mirror them, so
                    # purge via full re-sync and respill ONLY this round's
                    # specs: re-raising would make the loop requeue the
                    # whole batch, duplicating specs _schedule_spread
                    # already granted (at-most-once violation) and specs
                    # already parked infeasible.
                    logger.exception(
                        "pipeline submit failed; respilling round"
                    )
                    device_state.invalidate()
                    with self._cond:
                        self._deferred_rounds.pop(id(sched), None)
                        self._pending.extend(specs)
                        self._cond.notify_all()
                    return False
                return True
            pending = device_state.schedule_async(
                spread_threshold=self.hybrid_config.spread_threshold,
                shapes=(shape_rows, sids),
                ages=ages,
                locality=loc,
            )
            sched = (specs, shape_rows, sids, keys, pending, loc)
            rows = pending.result()
        else:
            demands = shape_rows[sids]
            prefer = np.zeros(len(specs), dtype=np.int32)
            force_spill = np.zeros(len(specs), dtype=bool)
            rows, _granted, _ = hybrid_schedule_reference(
                totals,
                avail,
                alive,
                demands,
                prefer,
                force_spill,
                config=self.hybrid_config,
                rng=self._rng,
            )
            # feasible-but-unavailable picks are not grants: park them
            rows = np.where(np.asarray(_granted), rows, -1)
            sched = (specs, shape_rows, sids, keys)
        self._fan_out_grants(sched, np.asarray(rows))
        if len(sched) > 4:
            self._handle_preempt(sched, sched[4].preempt_rows())
        return False

    def _round_shapes(self, batch: List[LeaseRequest], r: int):
        """Round demand prep off the per-shape dense-row cache:
        ``(specs, shape_rows f32[U,r], sids int32[B], infeasible,
        keys, ages f32[U])`` in the waterfall kernel's shape order.
        Replaces the per-spec ``dense()`` + stack + ``np.unique`` pass
        (O(B·R), the dominant host cost of a round at 10k nodes) with one
        dict lookup per spec and an O(U log U) sort over the round's
        unique shapes.

        Shape order: hardest-first (``hardest_first_order``), with
        STARVING shapes (integer wait-age buckets, from ``_shape_wait``)
        stably promoted to the front — a shape that has waited longest
        claims capacity first, the fairness half of the starvation term.
        With no waiting shapes the order is byte-identical to the
        single-objective prep. ``ages`` are normalized by
        ``sched_starve_rounds`` and ride the demand upload (kernel
        starvation discount + preemption arming).

        Locality (cfg.sched_w_locality > 0): specs whose top-level
        ObjectRef deps resolve to located, sized directory entries carry
        a per-node resident-bytes vector; specs with DIFFERENT vectors
        get their own kernel slot even at the same resource shape (a
        shuffle's reduce tasks share one shape but want different
        nodes), and the per-slot vectors ride the demand upload as the
        row-normalized f32[U, N] ``loc`` matrix (kernel locality bonus).
        Weight 0 — the default — skips every bit of this: slot keys,
        shape order, and the uploaded arrays are byte-identical to the
        pre-locality prep."""
        cache_r, cache = self._dense_cache
        if cache_r != r or len(cache) > 8192:
            # width change invalidates; the size cap bounds a workload
            # that never repeats a shape (per-task fractional demands) —
            # steady shape sets rebuild in one round
            cache = {}
            self._dense_cache = (r, cache)
        w_loc = float(cfg.sched_w_locality)
        loc_l: Optional[List[Optional[np.ndarray]]] = (
            [] if w_loc > 0 else None
        )
        loc_c = 0
        loc_by_spec: Dict[int, Optional[np.ndarray]] = {}
        if loc_l is not None:
            # ONE brief lock acquisition snapshots just the directory
            # facts ((size, view rows) per unique dep); the O(deps)
            # vector builds run lock-free below — neither a per-spec
            # take/release nor holding the head's most contended lock
            # across ndarray writes survives shuffle-sized rounds
            dep_info: Dict[str, Optional[Tuple[float, Tuple[int, ...]]]] = {}
            with self._lock:
                loc_c = self.view.totals.shape[0]
                for spec in batch:
                    for dep in spec.deps:
                        if dep in dep_info:
                            continue
                        e = self._objects.get(dep)
                        if e is None or not e.size or not e.locations:
                            dep_info[dep] = None
                            continue
                        rows_t = []
                        for nid in e.locations:
                            row = self.view.row_if_known(nid)
                            if row is not None and row < loc_c:
                                rows_t.append(row)
                        dep_info[dep] = (
                            (float(e.size), tuple(rows_t))
                            if rows_t
                            else None
                        )
            for spec in batch:
                if not spec.deps:
                    continue
                vec: Optional[np.ndarray] = None
                for dep in spec.deps:
                    info = dep_info.get(dep)
                    if info is None:
                        continue
                    size, rows_t = info
                    if vec is None:
                        vec = np.zeros(loc_c, dtype=np.float32)
                    for row in rows_t:
                        vec[row] += size
                loc_by_spec[id(spec)] = vec
        slots: Dict[tuple, int] = {}
        rows_l: List[np.ndarray] = []
        keys_l: List[tuple] = []
        specs: List[LeaseRequest] = []
        sid_l: List[int] = []
        infeasible: List[LeaseRequest] = []
        for spec in batch:
            key = _shape_key_of(spec)
            if key in cache:
                row = cache[key]
            else:
                req = self._spec_req(spec)
                if any(c >= r and fp > 0 for c, fp in req.demands.items()):
                    row = None  # oversized at width r: infeasible for now
                else:
                    row = req.dense(r)
                cache[key] = row
            if row is None:
                infeasible.append(spec)
                continue
            if loc_l is None:
                skey: tuple = key
                lv = None
            else:
                lv = loc_by_spec.get(id(spec))
                # the byte signature splits slots ONLY between specs with
                # genuinely different residency; identical reduce fan-ins
                # (and every no-dep spec) still share one slot
                skey = (key, None if lv is None else lv.tobytes())
            slot = slots.get(skey)
            if slot is None:
                slot = len(rows_l)
                slots[skey] = slot
                rows_l.append(row)
                keys_l.append(key)
                if loc_l is not None:
                    loc_l.append(lv)
            specs.append(spec)
            sid_l.append(slot)
        if not specs:
            return specs, None, None, infeasible, None, None, None
        shape_rows = np.stack(rows_l).astype(np.float32, copy=False)
        sids = np.asarray(sid_l, dtype=np.int32)
        order = hardest_first_order(shape_rows)
        starve_rounds = max(1, int(cfg.sched_starve_rounds))
        with self._cond:  # _shape_wait is shared with the completion thread
            ages = np.asarray(
                [self._shape_wait.get(k, 0) / starve_rounds for k in keys_l],
                dtype=np.float32,
            )
        if ages.any():
            # starving-first, stable within equal age buckets (all-zero
            # ages leave the hardest-first order untouched)
            buckets = np.minimum(ages[order], 8.0).astype(np.int32)
            order = order[np.argsort(-buckets, kind="stable")]
        remap = np.empty(shape_rows.shape[0], dtype=np.int32)
        remap[order] = np.arange(shape_rows.shape[0], dtype=np.int32)
        keys = [keys_l[i] for i in order]
        loc = None
        if loc_l is not None and any(v is not None for v in loc_l):
            loc = np.zeros((len(loc_l), loc_c), dtype=np.float32)
            for i, lv in enumerate(loc_l):
                if lv is not None:
                    total = float(lv.sum())
                    if total > 0:
                        loc[i] = lv / total
            loc = loc[order]
        return (
            specs,
            shape_rows[order],
            remap[sids],
            infeasible,
            keys,
            ages[order],
            loc,
        )

    def _ensure_pipeline(self):
        """The completion-side of pipelined rounds; created on first use
        (scheduler thread only — no construction race)."""
        if self._pipeline is None:
            from ray_tpu.scheduler.pipeline import SchedulerPipeline

            self._pipeline = SchedulerPipeline(
                on_complete=self._finish_round,
                on_error=self._round_failed,
            )
        return self._pipeline

    def _finish_round(self, sched, rows: np.ndarray, round_ms: float) -> None:
        """Completion-thread half of a pipelined round: the dispatch side
        has long moved on to later rounds; this fans the read-back
        placements out into grants."""
        SCHED_ROUND_MS.observe(round_ms)
        try:
            from ray_tpu.util.tracing import SPANS

            SPANS.record(
                "sched_round",
                "scheduler",
                time.time() - round_ms / 1e3,
                round_ms / 1e3,
                pid="head",
                batch=len(sched[0]),
                placed=int((rows >= 0).sum()),
            )
        except Exception:  # noqa: BLE001 - observability only
            pass
        try:
            self._fan_out_grants(sched, rows)
            if len(sched) > 4:
                self._handle_preempt(sched, sched[4].preempt_rows())
        except Exception:  # noqa: BLE001 - must not reach _round_failed
            # a PARTIAL fan-out is not safely unwindable (unplaced specs
            # already parked, host deductions applied, some grants sent):
            # letting this reach the pipeline's on_error would respill
            # the whole round and double-schedule the handled specs.
            # _round_failed's respill-everything recovery is only correct
            # for result() failures, where nothing has happened yet.
            logger.exception("grant fan-out failed mid-round")
        finally:
            with self._cond:
                self._deferred_rounds.pop(id(sched), None)
                self._cond.notify_all()

    def _round_failed(self, sched, exc: Exception) -> None:
        """A pipelined round died (kernel/readback error): respill its
        specs to the pending queue — same recovery as a synchronous round
        raising in the scheduler loop. The dead round's deductions were
        committed to the resident avail at dispatch but will never reach
        the host mirror, so force a full device re-sync to purge the
        phantom capacity loss."""
        device_state = self._lazy_device._result
        if device_state is not None:
            device_state.invalidate()
        with self._cond:
            self._deferred_rounds.pop(id(sched), None)
            self._pending.extend(sched[0])
            self._cond.notify_all()

    def _fan_out_grants(self, sched, rows: np.ndarray) -> None:
        """Turn one round's placement rows into per-node grant batches.
        ``sched`` is a ``(specs, shape_rows, sids[, keys[, pending]])``
        round context (_round_shapes). Unplaced specs park (and pin their
        shape in the device ring); placements deduct from the host mirror
        in ONE vectorized scatter-subtract and group per node off one
        argsort — the per-spec lock/subtract/setdefault loop dominated
        the host cost of a full round at 10k nodes. Shape wait-ages bump
        for shapes the round left (partly) unplaced and clear for fully
        placed ones (the starvation term's input)."""
        specs, shape_rows, sids = sched[0], sched[1], sched[2]
        keys = sched[3] if len(sched) > 3 else None
        placed_mask = rows >= 0
        if keys is not None:
            u = shape_rows.shape[0]
            total_per_shape = np.bincount(sids, minlength=u)
            placed_per_shape = np.bincount(
                sids[placed_mask], minlength=u
            )
            # aggregate per shape KEY first: locality slot-splitting can
            # put the same resource key in several kernel slots, and the
            # class's progress must be judged across ALL of them — a
            # per-slot loop would let an unplaced slot re-age a class
            # another slot just served (order-dependent starvation)
            per_key: Dict[tuple, List[int]] = {}
            for i, key in enumerate(keys):
                if total_per_shape[i] == 0:
                    continue
                ent = per_key.get(key)
                if ent is None:
                    ent = per_key[key] = [0, 0]
                ent[0] += int(placed_per_shape[i])
                ent[1] += int(total_per_shape[i])
            # under the lock: the scheduler thread (_round_shapes ages
            # read, ring-path bumps), RPC threads (QueryState), and this
            # completion thread all touch the wait tables
            with self._cond:
                for key, (placed_n, total_n) in per_key.items():
                    if placed_n > 0:
                        # the CLASS made progress this round: it is not
                        # starving, even with instances left over —
                        # aging a continuously-served shape made it
                        # "starve" and preempt its own running peers in
                        # a kill/requeue livelock
                        self._shape_wait.pop(key, None)
                        if placed_n >= total_n:
                            self._preempt_cooldown.pop(key, None)
                    else:
                        self._shape_wait[key] = (
                            self._shape_wait.get(key, 0) + 1
                        )
                if len(self._shape_wait) > 4096:
                    # bound the tables: entries normally clear on full
                    # placement; cancelled-last-spec shapes can leak —
                    # evict the youngest half (oldest = closest to
                    # starving, keep) and their cooldown rows with them
                    for k in sorted(
                        self._shape_wait, key=self._shape_wait.get
                    )[:2048]:
                        self._shape_wait.pop(k, None)
                        self._preempt_cooldown.pop(k, None)
                if len(self._preempt_cooldown) > 4096:
                    # cooldowns for shapes that drained while parked
                    # have no other reaper: drop the expired ones
                    now = time.monotonic()
                    for k in [
                        k
                        for k, t in self._preempt_cooldown.items()
                        if t <= now
                    ]:
                        self._preempt_cooldown.pop(k, None)
        unplaced = [specs[i] for i in np.flatnonzero(~placed_mask)]
        if unplaced:
            with self._cond:
                if self._cancelled_leases:
                    # cancelled / owner-reaped while the round was in
                    # flight: the dispatch-time filter in _send_grants
                    # only covers the granted half — drop, don't park
                    kept = []
                    for s in unplaced:
                        if s.task_id in self._cancelled_leases:
                            self._cancelled_leases.discard(s.task_id)
                        else:
                            kept.append(s)
                    unplaced = kept
                self._infeasible.extend(unplaced)
            if unplaced:
                self._ring_park_specs(unplaced)
        idx = np.flatnonzero(placed_mask)
        if idx.size == 0:
            return
        demands_mat = shape_rows[sids[idx]]
        row_arr = rows[idx].astype(np.int64)
        loc = sched[5] if len(sched) > 5 else None
        if loc is not None:
            # locality accounting: loc rows are normalized residency
            # fractions, so loc[slot, chosen_row] IS the fraction of this
            # lease's input bytes already on its node
            slot_arr = sids[idx]
            scored = loc[slot_arr].sum(axis=1) > 0
            n_scored = int(scored.sum())
            if n_scored:
                frac = loc[slot_arr, np.clip(row_arr, 0, loc.shape[1] - 1)]
                SCHED_LOCALITY_SCORED.inc(n_scored)
                SCHED_LOCALITY_HIT_FRAC.inc(float(frac[scored].sum()))
        order = np.argsort(row_arr, kind="stable")
        srt = row_arr[order]
        starts = np.flatnonzero(
            np.concatenate([[True], srt[1:] != srt[:-1]])
        )
        grants: Dict[str, List[LeaseRequest]] = {}
        row_to_node: Dict[int, str] = {}
        with self._lock:
            # optimistic deduction so later rounds see the placement; the
            # agent's authoritative report will overwrite the rows.
            self.view.subtract_many(row_arr, demands_mat)
            for k, start in enumerate(starts):
                end = starts[k + 1] if k + 1 < len(starts) else srt.size
                node_id = self.view.node_id(int(srt[start]))
                row_to_node[int(srt[start])] = node_id
                grants[node_id] = [
                    specs[idx[order[j]]] for j in range(start, end)
                ]
        self._send_grants(grants)
        if cfg.sched_explain:
            try:
                self._note_explanations(sched, rows, idx, row_arr, row_to_node)
            except Exception:  # noqa: BLE001 - attribution is best-effort
                logger.exception("placement attribution failed")

    def _note_explanations(
        self,
        sched,
        rows: np.ndarray,
        idx: np.ndarray,
        row_arr: np.ndarray,
        row_to_node: Dict[int, str],
    ) -> None:
        """Scheduler decision attribution (ISSUE 15): record, per placed
        spec, the five per-term cost contributions of its winning node
        (``hybrid.TERM_NAMES``) into the bounded explanation table and a
        SCHEDULED task event — so both ``QueryState explain_placement``
        and the Chrome-trace export answer "why THIS node". Kernel
        rounds carry exact terms read back with the placements; host
        golden-model rounds record the placement with zeroed terms
        (single-objective by construction), labeled by source."""
        from ray_tpu.scheduler.hybrid import TERM_NAMES

        specs = sched[0]
        pending = sched[4] if len(sched) > 4 else None
        terms = pending.terms_rows() if pending is not None else None
        source = "kernel" if terms is not None else "host"
        now = time.time()
        entries: List[Tuple[str, dict]] = []
        for j, i in enumerate(np.asarray(idx)):
            spec = specs[int(i)]
            node_id = row_to_node.get(int(row_arr[j]))
            if node_id is None:
                continue
            if terms is not None:
                tvec = terms[int(i)]
                tdict = {
                    name: float(tvec[t]) for t, name in enumerate(TERM_NAMES)
                }
            else:
                tdict = {name: 0.0 for name in TERM_NAMES}
                tdict["starve_discount"] = 1.0
            trace = getattr(spec, "trace", None) or {}
            entries.append(
                (
                    spec.task_id,
                    {
                        "task_id": spec.task_id,
                        "name": spec.name,
                        "node": node_id,
                        "source": source,
                        "terms": tdict,
                        "trace_id": trace.get("trace_id"),
                        "ts": now,
                    },
                )
            )
            self.events.record(
                spec.task_id,
                spec.name,
                "SCHEDULED",
                node_id,
                sched_terms=tdict,
                **_trace_args(spec),
            )
        if not entries:
            return
        keep = max(64, int(cfg.sched_explain_keep))
        with self._explain_lock:
            for tid, ent in entries:
                self._explain[tid] = ent
                self._explain.move_to_end(tid)
            while len(self._explain) > keep:
                self._explain.popitem(last=False)

    def explain_placement(self, task_id: str) -> Optional[dict]:
        """The recorded decision attribution for one scheduled task (or
        None: never kernel-scheduled, evicted, or explain off)."""
        with self._explain_lock:
            return self._explain.get(task_id)

    def _ring_park_specs(self, specs: List[LeaseRequest]) -> None:
        """Pin freshly-parked kernel shapes in the on-device parked-demand
        ring so their retries run count-driven off resident rows
        (device.py ring_schedule) instead of re-uploading demand."""
        device_state = self._lazy_device._result
        if device_state is None or device_state.ring_slots <= 0:
            return
        with self._lock:
            r = self.view.totals.shape[1]
        for spec in specs:
            if spec.strategy is not None or spec.target_node or spec.pg_reservation:
                continue
            req = self._spec_req(spec)
            if any(c >= r and fp > 0 for c, fp in req.demands.items()):
                continue
            device_state.ring_park(_shape_key_of(spec), req.dense(r))

    # ------------------------------------------------------------------
    # preemption / migration (ISSUE 7): the kernel NOMINATES (per
    # starving shape, the lowest-cost feasible-by-totals node); the head
    # maps the node to concrete victim leases and kill-and-requeues
    # through the PR 5 lineage/fate-sharing machinery. State machine per
    # victim (COMPONENTS.md):
    #   queued-on-agent  --CancelLease--> requeued (no attempt burned)
    #   worker_lease     --revoke------->  owner spills to head path
    #   running retryable --force kill--> worker-death report -->
    #                                     requeued via _preempted_leases
    #                                     (no attempt burned)
    #   running max_retries=0            NEVER a victim (at-most-once)
    # ------------------------------------------------------------------

    def _nominate(self, key: tuple, row: int, need: np.ndarray) -> bool:
        """One nomination: per-shape cooldown gate, metrics, node
        resolution, and the async victim fan-out. The ONE copy of the
        nomination policy, shared by the round-kernel and ring paths.
        Returns False when the dispatch pool is gone (caller stops)."""
        now = time.monotonic()
        with self._lock:  # cooldown table is shared across threads
            if self._preempt_cooldown.get(key, 0.0) > now:
                return True
            self._preempt_cooldown[key] = (
                now + float(cfg.sched_preempt_cooldown_s)
            )
            self.metrics["preempt_nominations"] += 1
            if row >= self.view.num_nodes:
                node_id = None
            else:
                node_id = self.view.node_id(row)
        SCHED_PREEMPT_NOMINATED.inc()
        if node_id is None:
            return True
        # victim kills do RPCs: off the completion thread
        try:
            self._dispatch_pool.submit(
                self._preempt_on_node, node_id, need, key
            )
        except RuntimeError:  # dispatch pool shut down
            return False
        return True

    def _handle_preempt(self, sched, pre_rows: Optional[np.ndarray]) -> None:
        """Fan one round's preemption nominations out into victim kills.
        ``sched`` = (specs, shape_rows, sids, keys, pending)."""
        if pre_rows is None or not cfg.sched_preempt:
            return
        keys, shape_rows = sched[3], sched[1]
        for u, row in enumerate(np.asarray(pre_rows)):
            if row < 0 or keys is None or u >= len(keys):
                continue
            if not self._nominate(keys[u], int(row), shape_rows[u]):
                return

    def _handle_ring_preempt(
        self, nominations: List[Tuple[tuple, int, np.ndarray]]
    ) -> None:
        """Ring-round nominations: (shape key, node row, dense demand)
        triples from ``_unpark_via_ring`` — same cooldown + victim
        fan-out as the round-kernel path (``_nominate``)."""
        if not cfg.sched_preempt:
            return
        for key, row, need in nominations:
            if not self._nominate(key, row, need):
                return

    def _pick_preemption_victims(
        self, node_id: str, need: np.ndarray
    ) -> Tuple[List[str], List[Tuple[LeaseRequest, bool]]]:
        """(worker-lease victims, (task spec, may_force) victims) on
        ``node_id``, lowest-cost-first, accumulating until the freed
        demand covers ``need`` on its demanded columns (bounded by
        sched_preempt_max_per_round). Lowest cost = least work lost:
        worker leases (spill, nothing re-executes) before task leases
        (smallest resource footprint first). Running max_retries=0 work
        is never force-killable; queued work of any retry class is (it
        has not started — requeue is not re-execution). Victims must be
        STRICTLY CHEAPER than the starving shape (demand sum): a shape
        preempting peers of its own size just swaps who waits while
        losing work — observed as a kill/requeue livelock. Caller need
        not hold the lock."""
        cols = need > 0
        need_sum = float(need.sum())
        limit = max(1, int(cfg.sched_preempt_max_per_round))
        lease_victims: List[str] = []
        task_victims: List[Tuple[LeaseRequest, bool]] = []
        freed = np.zeros_like(need)
        with self._cond:
            cands: List[Tuple[float, str, object]] = []
            for lid, e in self._task_leases.items():
                if e.get("node_id") != node_id or e["state"] != "active":
                    continue
                spec = self._leases.get(lid)
                d = (
                    self._spec_req(spec).dense(need.shape[0])
                    if spec is not None
                    else self.vocab.pack(e["resources"])[: need.shape[0]]
                )
                if not (d[cols] > 0).any():
                    continue  # frees nothing the starving shape needs
                if float(d.sum()) >= need_sum:
                    continue  # not strictly cheaper: peer churn, skip
                cands.append((float(d.sum()), "lease", (lid, d)))
            for lid, (spec, nid) in self._in_flight.items():
                if nid != node_id or spec.kind != "task":
                    continue
                d = self._spec_req(spec).dense(need.shape[0])
                if not (d[cols] > 0).any():
                    continue
                if float(d.sum()) >= need_sum:
                    continue  # not strictly cheaper: peer churn, skip
                # +1.0 sort bias: prefer worker leases at equal footprint
                cands.append((float(d.sum()) + 1.0, "task", (spec, d)))
            cands.sort(key=lambda c: c[0])
            for _, kind, payload in cands:
                if (
                    len(lease_victims) + len(task_victims) >= limit
                    or np.all(freed[cols] >= need[cols])
                ):
                    break
                if kind == "lease":
                    lid, d = payload
                    lease_victims.append(lid)
                    freed = freed + d
                else:
                    spec, d = payload
                    may_force = (
                        bool(cfg.sched_preempt_running)
                        and spec.attempt < spec.max_retries
                    )
                    task_victims.append((spec, may_force))
                    freed = freed + d
        return lease_victims, task_victims

    def _preempt_on_node(
        self, node_id: str, need: np.ndarray, shape_key: tuple
    ) -> None:
        """Execute one nomination: revoke/kill the chosen victims so the
        starving shape's next round finds capacity on ``node_id``."""
        lease_victims, task_victims = self._pick_preemption_victims(
            node_id, need
        )
        self._evict_victims(node_id, lease_victims, task_victims, shape_key)

    def _evict_victims(
        self,
        node_id: str,
        lease_victims: List[str],
        task_victims: List[Tuple[LeaseRequest, bool]],
        shape_key: tuple,
    ) -> None:
        """The execution half of a preemption/migration: revoke worker
        leases (spill, nothing re-executes), CancelLease(force=False)
        queued task leases (requeue, no attempt burned), and force-kill
        running RETRYABLE tasks via the ``_preempted_leases`` attempt-free
        requeue path. Shared by shape-starvation preemption (PR 7, victims
        strictly cheaper than the starving shape) and drain-ahead
        migration (PR 19, every movable lease on a retiring node)."""
        for lid in lease_victims:
            with self._cond:
                if self._drop_task_lease_locked(lid) is None:
                    continue
                self.metrics["task_leases_revoked"] += 1
                TASK_LEASE_REVOKED.inc()
                self.metrics["preemptions"] += 1
                SCHED_PREEMPTIONS.inc(labels={"kind": "worker_lease"})
                self._cond.notify_all()
            self._wal_flush()
            logger.info(
                "preempted worker lease %s on %s for starving shape %r",
                lid[:8],
                node_id,
                shape_key,
            )
            self._agent_return_lease(node_id, lid)
        if not task_victims:
            return
        client = self._clients.get(node_id)
        if client is None:
            return
        for spec, may_force in task_victims:
            lid = spec.task_id
            try:
                reply = client.call(
                    "CancelLease", {"task_id": lid, "force": False},
                    timeout=10.0,
                )
            except RpcError:
                continue  # unreachable: the health path owns this node
            if reply.get("cancelled"):
                # still queued agent-side: it never started — requeue
                # with no attempt burned (a preemption is a scheduler
                # action, not a task failure)
                with self._cond:
                    self._in_flight.pop(lid, None)
                    spec.target_node = None
                    self._pending.append(spec)
                    self.metrics["preemptions"] += 1
                    SCHED_PREEMPTIONS.inc(labels={"kind": "queued"})
                    self._cond.notify_all()
                logger.info(
                    "preempted queued lease %s on %s (requeued)",
                    lid[:8],
                    node_id,
                )
                continue
            if not may_force:
                continue  # running and not safely re-executable: skip
            # running retryable task: kill-and-requeue. The flag makes
            # the agent's worker-death "failed" report requeue WITHOUT
            # consuming a retry attempt (_h_report_seals).
            with self._cond:
                self._preempted_leases.add(lid)
            try:
                reply = client.call(
                    "CancelLease", {"task_id": lid, "force": True},
                    timeout=10.0,
                )
                if reply.get("cancelled"):
                    self.metrics["preemptions"] += 1
                    SCHED_PREEMPTIONS.inc(labels={"kind": "running"})
                    logger.info(
                        "preempted running lease %s on %s (migrating)",
                        lid[:8],
                        node_id,
                    )
                else:
                    # finished (or vanished) before the kill landed
                    with self._cond:
                        self._preempted_leases.discard(lid)
            except RpcError:
                with self._cond:
                    self._preempted_leases.discard(lid)

    # ------------------------------------------------------------------
    # drain-ahead retirement (PR 19 unified elasticity plane)
    # ------------------------------------------------------------------
    def begin_node_drain(
        self, node_id: str, deadline_s: Optional[float] = None
    ) -> bool:
        """Mark ``node_id`` draining: its NodeReport availability is
        clamped to zero (no new placements) and its ClusterView row is
        zeroed immediately so in-flight scheduling rounds stop choosing
        it. Returns False for unknown/dead nodes."""
        if deadline_s is None:
            deadline_s = float(cfg.elastic_drain_deadline_s)
        with self._cond:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return False
            if node_id in self._draining_nodes:
                return True
            self._draining_nodes[node_id] = time.monotonic() + deadline_s
            self.view.update_available(
                node_id, {k: 0.0 for k in node.resources}
            )
            self._pgs_dirty = True
            self._cond.notify_all()
        logger.info(
            "node %s draining (deadline %.1fs)", node_id, deadline_s
        )
        return True

    def migrate_node_leases(self, node_id: str) -> int:
        """Drain-ahead migration: move every movable lease off a node
        selected for retirement BEFORE the drain deadline. Unlike
        starvation preemption there is no strictly-cheaper constraint —
        the node is going away, so everything that can be relocated
        without losing completed work is: worker leases spill, queued
        tasks requeue, running retryable tasks kill-and-requeue with no
        attempt burned. Running max_retries=0 work is left to finish
        inside the deadline (forcing it would turn a planned retirement
        into a task failure). Returns the victim count."""
        lease_victims: List[str] = []
        task_victims: List[Tuple[LeaseRequest, bool]] = []
        with self._cond:
            for lid, e in self._task_leases.items():
                if e.get("node_id") == node_id and e["state"] == "active":
                    lease_victims.append(lid)
            for lid, (spec, nid) in self._in_flight.items():
                if nid != node_id or spec.kind != "task":
                    continue
                task_victims.append(
                    (spec, spec.attempt < spec.max_retries)
                )
        if lease_victims or task_victims:
            self._evict_victims(
                node_id, lease_victims, task_victims, ("drain", node_id)
            )
        return len(lease_victims) + len(task_victims)

    def node_drained(self, node_id: str) -> bool:
        """True once nothing leased remains on a draining node."""
        with self._cond:
            for e in self._task_leases.values():
                if e.get("node_id") == node_id and e["state"] == "active":
                    return False
            for _, (spec, nid) in self._in_flight.items():
                if nid == node_id:
                    return False
        return True

    def finish_node_drain(self, node_id: str, retire: bool) -> None:
        """Close a drain: either the provider terminated the node
        (``retire=True`` — declare it dead so leases/gangs/objects run
        their death paths) or the drain was cancelled (``retire=False``
        — the next NodeReport restores its advertised availability)."""
        with self._cond:
            self._draining_nodes.pop(node_id, None)
        if retire:
            self._on_node_death(node_id)

    def _dispatch_batch_blocking(
        self, specs: List[LeaseRequest], node_id: str, client: RpcClient
    ) -> None:
        try:
            reply = client.call("ExecuteLeaseBatch", specs, timeout=60.0)
        except RpcError:
            with self._cond:
                for s in specs:
                    self._in_flight.pop(s.task_id, None)
            for s in specs:
                self._retry_or_fail(s, f"agent {node_id} unreachable")
            return
        rejected = []
        for s, status in zip(specs, reply["statuses"]):
            if status == "granted":
                self.events.record(
                    s.task_id, s.name, "RUNNING", node_id, **_trace_args(s)
                )
            else:
                rejected.append(s)
        if rejected:
            # stale view: grant-or-reject → spill back to the queue
            with self._cond:
                self.metrics["leases_spilled_back"] += len(rejected)
                for s in rejected:
                    self._in_flight.pop(s.task_id, None)
                if reply.get("available") is not None:
                    node = self.nodes.get(node_id)
                    if node is not None and node.alive:
                        self.view.update_available(node_id, reply["available"])
                self._pending.extend(rejected)
                self._cond.notify_all()

    def _schedule_spread(self, specs: List[LeaseRequest]) -> None:
        """Distinct SPREAD policy: round-robin over feasible alive nodes
        (spread_scheduling_policy.cc:26 analog), vectorized over the batch
        with in-batch deductions so one round can't stack one node."""
        with self._lock:
            t0, a0, al0 = self.view.active_arrays()
            totals, avail, alive = t0.copy(), a0.copy(), al0.copy()
            node_ids = [
                self.view.node_id(i) for i in range(self.view.num_nodes)
            ]
        n = len(node_ids)
        if n == 0 or not alive.any():
            with self._cond:
                self._infeasible.extend(specs)
            return
        r = totals.shape[1]
        reqs = [self._spec_req(s) for s in specs]
        # demands naming a resource no node has ever reported are
        # unplaceable until the cluster changes (same guard as the kernel)
        sched: List[Tuple[LeaseRequest, np.ndarray]] = []
        with self._cond:
            for spec, req in zip(specs, reqs):
                if any(c >= r and fp > 0 for c, fp in req.demands.items()):
                    self._infeasible.append(spec)
                else:
                    sched.append((spec, req.dense(r)))
        if not sched:
            return
        specs = [s for s, _ in sched]
        demands = np.stack([d for _, d in sched])
        grants: Dict[str, List[LeaseRequest]] = {}
        order = np.arange(n)
        for i, spec in enumerate(specs):
            feasible = (avail >= demands[i]).all(axis=1) & alive
            if spec.strategy == "RANDOM":
                # random_scheduling_policy.cc analog: uniform over feasible
                cand = np.flatnonzero(feasible)
                if cand.size == 0:
                    with self._cond:
                        self._infeasible.append(spec)
                    continue
                row = int(self._rng.choice(cand))
            else:
                rot = np.roll(order, -self._spread_rr)
                cand = rot[feasible[rot]]
                if cand.size == 0:
                    with self._cond:
                        self._infeasible.append(spec)
                    continue
                row = int(cand[0])
                self._spread_rr = (row + 1) % n
            avail[row] -= demands[i]
            with self._lock:
                self.view.subtract(row, demands[i])
            grants.setdefault(node_ids[row], []).append(spec)
        self._send_grants(grants)

    def _send_grants(self, grants: Dict[str, List[LeaseRequest]]) -> None:
        if self._cancelled_leases:
            with self._cond:
                filtered: Dict[str, List[LeaseRequest]] = {}
                for nid, specs in grants.items():
                    keep = []
                    for s in specs:
                        if s.task_id in self._cancelled_leases:
                            self._cancelled_leases.discard(s.task_id)
                        else:
                            keep.append(s)
                    if keep:
                        filtered[nid] = keep
                grants = filtered
        for node_id, specs in grants.items():
            with self._lock:
                client = self._clients.get(node_id)
                node = self.nodes.get(node_id)
                for s in specs:
                    s.target_node = node_id
                    self._in_flight[s.task_id] = (s, node_id)
            if client is None or node is None or not node.alive:
                with self._cond:
                    for s in specs:
                        self._in_flight.pop(s.task_id, None)
                    self._pending.extend(specs)
                    self._cond.notify_all()
                continue
            try:
                self._prestart_hint(client, specs)
                self._dispatch_pool.submit(
                    self._dispatch_batch_blocking, specs, node_id, client
                )
            except RuntimeError:
                # dispatch pool shut down mid-round: respill like a dead
                # client. Raising here would make the caller's recovery
                # respill the WHOLE round — duplicating specs already
                # submitted to other nodes (at-most-once violation for
                # max_retries=0 leases).
                with self._cond:
                    for s in specs:
                        self._in_flight.pop(s.task_id, None)
                    self._pending.extend(specs)
                    self._cond.notify_all()

    def _prestart_hint(
        self, client: RpcClient, specs: List[LeaseRequest]
    ) -> None:
        """Actor creations pin workers for life: tell the target agent how
        many are inbound so replacement capacity warms WHILE the leases
        are in flight instead of after each one pins its worker
        (worker_pool.cc PrestartWorkers semantics)."""
        n = sum(1 for s in specs if s.kind == "actor_creation")
        if n:
            self._dispatch_pool.submit(
                _best_effort,
                client.call,
                "PrestartWorkers",
                {"count": n},
            )

    def _pick_labeled_node(self, strat, resources) -> Optional[str]:
        """Label-selector placement (node_label_scheduling_policy.cc
        analog): hard selectors filter, resource feasibility filters
        (the reference policy only considers feasible labeled nodes),
        soft selectors prefer; ties go round-robin."""
        from ray_tpu.scheduler.labels import match_labels

        req = ResourceRequest.from_map(self.vocab, resources)
        with self._lock:
            r = self.view.totals.shape[1]
            if any(c >= r and fp > 0 for c, fp in req.demands.items()):
                return None  # unknown resource: no node can fit it yet
            d = req.dense(r)
            avail = self.view.active_arrays()[1]
            hard = [
                nid
                for nid, node in self.nodes.items()
                if node.alive
                and match_labels(node.labels, strat.hard)
                and (avail[self.view.row_of(nid)] >= d).all()
            ]
            preferred = [
                nid
                for nid in hard
                if match_labels(self.nodes[nid].labels, strat.soft)
            ]
        pool = preferred or hard
        if not pool:
            return None
        self._label_rr += 1
        return pool[self._label_rr % len(pool)]

    def _route_constrained(self, spec: LeaseRequest):
        """Actor methods, node affinity, label selectors, and PG-bound
        leases bypass the kernel (composite policy dispatch,
        composite_scheduling_policy.cc); SPREAD gets its own round-robin
        pass."""
        from ray_tpu.core.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
            NodeLabelSchedulingStrategy,
            PlacementGroupSchedulingStrategy,
        )

        if spec.kind == "actor_method":
            info = self._actors.get(spec.actor_id)
            if info is None or info.state == "DEAD":
                self._seal_error_ids(
                    spec.return_ids,
                    RuntimeError(f"actor {spec.actor_id} is dead"),
                )
                return "done"
            if info.state != "ALIVE":
                with self._cond:
                    self._infeasible.append(spec)
                return "done"
            self._dispatch(spec, info.node_id)
            return "done"
        strat = spec.strategy
        if strat in ("SPREAD", "RANDOM"):
            return "spread"  # both use the vectorized round-robin pass
        if isinstance(strat, NodeLabelSchedulingStrategy):
            node_id = self._pick_labeled_node(strat, spec.resources)
            if node_id is None:
                if strat.hard:
                    # no labeled node yet — parked until membership changes
                    with self._cond:
                        self._infeasible.append(spec)
                    return "done"
                return "kernel"  # soft-only: any node will do
            self._dispatch(spec, node_id)
            return "done"
        if isinstance(strat, NodeAffinitySchedulingStrategy):
            node = self.nodes.get(strat.node_id)
            if node is not None and node.alive:
                self._dispatch(spec, strat.node_id)
                return "done"
            if strat.soft:
                return "kernel"
            self._seal_error_ids(
                spec.return_ids,
                RuntimeError(
                    f"node affinity target {strat.node_id} is dead/unknown"
                ),
            )
            return "done"
        if isinstance(strat, PlacementGroupSchedulingStrategy):
            pg = self._pgs.get(strat.placement_group.id)
            if pg is None or pg.removed:
                self._seal_error_ids(
                    spec.return_ids, RuntimeError("placement group removed")
                )
                return "done"
            if not pg.ready.is_set():
                with self._cond:
                    self._infeasible.append(spec)
                return "done"
            idx = strat.placement_group_bundle_index
            if idx is None or idx < 0:
                idx = self._pick_pg_bundle(pg, spec.resources)
            if idx is None:
                with self._cond:
                    self._infeasible.append(spec)
                return "done"
            spec.pg_reservation = (pg.pg_id, int(idx))
            self._dispatch(spec, pg.node_per_bundle[int(idx)])
            return "done"
        return "kernel"

    def _pick_pg_bundle(self, pg: _PGState, resources: Dict[str, float]):
        for i, b in enumerate(pg.bundles):
            if all(b.get(k, 0.0) >= v for k, v in resources.items()):
                return i
        return None

    def _dispatch(self, spec: LeaseRequest, node_id: str) -> None:
        spec.target_node = node_id
        with self._lock:
            client = self._clients.get(node_id)
            node = self.nodes.get(node_id)
            self._in_flight[spec.task_id] = (spec, node_id)
        if client is None or node is None or not node.alive:
            with self._cond:
                self._in_flight.pop(spec.task_id, None)
                self._pending.append(spec)
            return
        if spec.kind == "actor_method":
            # per-actor single-flight sender: preserves driver submission
            # order end-to-end (the reference's per-actor sequence-numbered
            # ordered queue, task_execution/ordered_actor_task_execution_queue.cc)
            with self._lock:
                q = self._actor_send.setdefault(spec.actor_id, deque())
                q.append((spec, node_id, client))
                if spec.actor_id in self._actor_sending:
                    return
                self._actor_sending.add(spec.actor_id)
            self._dispatch_pool.submit(self._drain_actor_sends, spec.actor_id)
            return
        if spec.kind == "actor_creation":
            # constrained routes (PG / affinity / labels) bypass
            # _send_grants; they still warrant a warm-pool hint
            self._prestart_hint(client, [spec])
        self._dispatch_pool.submit(self._dispatch_blocking, spec, node_id, client)

    def _drain_actor_sends(self, actor_id: str) -> None:
        """Single-flight per-actor sender. Everything queued while the
        previous RPC was in flight ships as ONE ordered ExecuteLeaseBatch —
        submission order is preserved (the reference's sequence-numbered
        actor queue), but the wire cost amortizes under load."""
        while True:
            with self._lock:
                q = self._actor_send.get(actor_id)
                if not q:
                    self._actor_sending.discard(actor_id)
                    return
                items = []
                while q and len(items) < 128:
                    items.append(q.popleft())
            if len(items) == 1:
                spec, node_id, client = items[0]
                self._dispatch_blocking(spec, node_id, client)
                continue
            # one batch per (node, client) run, preserving order
            i = 0
            while i < len(items):
                j = i
                client = items[i][2]
                node_id = items[i][1]
                while j < len(items) and items[j][2] is client:
                    j += 1
                self._dispatch_actor_batch(
                    [it[0] for it in items[i:j]], node_id, client
                )
                i = j

    def _dispatch_actor_batch(
        self, specs: List[LeaseRequest], node_id: str, client: RpcClient
    ) -> None:
        try:
            reply = client.call("ExecuteLeaseBatch", specs, timeout=60.0)
        except RpcError:
            with self._cond:
                for s in specs:
                    self._in_flight.pop(s.task_id, None)
            for s in specs:
                self._retry_or_fail(s, f"agent {node_id} unreachable")
            return
        for s, status in zip(specs, reply["statuses"]):
            if status == "granted":
                self.events.record(
                    s.task_id, s.name, "RUNNING", node_id, **_trace_args(s)
                )
            else:
                # actor gone on that agent: fail/requeue via the normal path
                with self._cond:
                    self._in_flight.pop(s.task_id, None)
                self._retry_or_fail(s, f"actor lease rejected by {node_id}")

    def _dispatch_blocking(
        self, spec: LeaseRequest, node_id: str, client: RpcClient
    ) -> None:
        try:
            reply = client.call("ExecuteLease", spec, timeout=30.0)
        except RpcError:
            with self._cond:
                self._in_flight.pop(spec.task_id, None)
            self._retry_or_fail(spec, f"agent {node_id} unreachable")
            return
        if reply.get("status") == "granted":
            self.events.record(
                spec.task_id, spec.name, "RUNNING", node_id,
                **_trace_args(spec)
            )
        if reply.get("status") == "reject":
            # stale view: grant-or-reject → spill back to the queue
            with self._cond:
                self.metrics["leases_spilled_back"] += 1
                self._in_flight.pop(spec.task_id, None)
                if reply.get("available") is not None:
                    node = self.nodes.get(node_id)
                    if node is not None and node.alive:
                        self.view.update_available(node_id, reply["available"])
                self._pending.append(spec)
                self._cond.notify_all()

    # ------------------------------------------------------------------
    # actors (GcsActorManager / GcsActorScheduler analog)
    # ------------------------------------------------------------------
    def _h_create_actor(self, req: dict) -> dict:
        spec: LeaseRequest = req["spec"]
        with self._cond:
            if spec.actor_id in self._actors:
                # at-least-once redelivery (a pipelined ClientBatch whose
                # reply was lost re-sends): creating twice would run ctor
                # side effects twice and leak a pinned worker
                return {"actor_id": spec.actor_id}
        name = req.get("name")
        info = ActorInfo(
            actor_id=spec.actor_id,
            name=name,
            class_name=req.get("class_name", ""),
            max_restarts=req.get("max_restarts", 0),
            lifetime=req.get("lifetime"),
            owner_client=spec.client_id,
        )
        spec.actor_meta = {
            "name": name,
            "max_restarts": info.max_restarts,
            "max_concurrency": req.get("max_concurrency"),
            "concurrency_groups": req.get("concurrency_groups", {}),
            # ride to the agent so re-attach after an unpersisted head
            # restart keeps disconnect-reaping semantics
            "lifetime": info.lifetime,
            "owner_client": info.owner_client,
        }
        # ctor args stay pinned for the actor's whole life (restarts replay
        # the creation payload); released when the actor is finally DEAD
        self._register_return_holder(spec)
        with self._cond:
            if name:
                if name in self._named_actors:
                    raise ValueError(f"actor name {name!r} already taken")
                self._named_actors[name] = spec.actor_id
            self._actors[spec.actor_id] = info
            self._actor_specs[spec.actor_id] = spec
            self._leases[spec.task_id] = spec
            self._pending.append(spec)
            self._wal(("actor", dict(vars(info)), spec, name))
            self._cond.notify_all()
        self._wal_flush()
        self.mark_dirty()
        return {"actor_id": spec.actor_id}

    def _mark_actor_alive(self, actor_id: str, node_id: str, address: str) -> None:
        with self._cond:
            info = self._actors.get(actor_id)
            if info is None:
                return
            if info.state == "DEAD":
                # killed while its creation lease was still in flight: don't
                # resurrect — tear the instance down on the hosting agent.
                client = self._clients.get(node_id)
                if client is not None:
                    self._dispatch_pool.submit(
                        lambda: _best_effort(
                            client.call, "KillActor", {"actor_id": actor_id}
                        )
                    )
                return
            info.state = "ALIVE"
            info.node_id = node_id
            info.address = address
            # parked actor-method leases can now route
            self._pending.extend(self._infeasible)
            self._infeasible.clear()
            self._cond.notify_all()
        self.mark_dirty()

    def _h_cancel_lease(self, req: dict) -> dict:
        """Best-effort cancel by return-object id (ray.cancel parity):
        queued work (pending / infeasible / mid-schedule / agent
        dep-waiting) is dropped and its returns sealed cancelled; running
        tasks are not preempted unless force=True kills the worker — the
        reference's non-force semantics."""
        oid = req["object_id"]
        force = bool(req.get("force"))
        with self._cond:
            entry = self._objects.get(oid)
            lid = entry.creating_lease if entry is not None else None
            spec = self._leases.get(lid) if lid else None
            if spec is None:
                return {"cancelled": False, "reason": "unknown lease"}
            dropped = False
            for q in (self._pending, self._infeasible):
                for s in list(q):
                    if s.task_id == lid:
                        q.remove(s)
                        dropped = True
            # mid-schedule: the round popped it out of every queue above
            # (this window spans the first round's XLA bring-up and any
            # dispatched-but-uncompleted pipelined round) — flag it for
            # the dispatch-time filter
            if not dropped and any(
                s.task_id == lid
                for q in (
                    self._scheduling_batch,
                    *self._deferred_rounds.values(),
                )
                for s in q
            ):
                self._cancelled_leases.add(lid)
                dropped = True
            in_flight = self._in_flight.get(lid)
        if dropped:
            self._seal_error_ids(
                spec.return_ids, RuntimeError("task cancelled")
            )
            self._release_lease_pins(lid)
            return {"cancelled": True}
        if in_flight is not None:
            _, node_id = in_flight
            client = self._clients.get(node_id)
            if client is not None:
                if force:
                    # the kill trips the worker-death report; the flag
                    # tells the failure handler this was a cancel, not a
                    # crash to retry
                    with self._cond:
                        self._cancelled_leases.add(lid)
                try:
                    reply = client.call(
                        "CancelLease",
                        {"task_id": lid, "force": force},
                        timeout=10.0,
                    )
                    if reply.get("cancelled"):
                        with self._cond:
                            self._in_flight.pop(lid, None)
                        self._seal_error_ids(
                            spec.return_ids,
                            RuntimeError("task cancelled"),
                        )
                        self._release_lease_pins(lid)
                        return {"cancelled": True}
                except RpcError:
                    pass
                if force:
                    with self._cond:
                        self._cancelled_leases.discard(lid)
        return {"cancelled": False, "reason": "not queued"}

    def _h_pending_demands(self, req=None) -> List[Dict[str, float]]:
        """Queued + infeasible lease shapes and unplaced PG bundles — the
        autoscaler's demand source (GcsAutoscalerStateManager
        ClusterResourceState analog)."""
        with self._cond:
            parked: Dict[tuple, int] = {}
            deferred: Dict[tuple, int] = {}
            # mid-schedule leases count too, but a round can move a spec
            # into _infeasible/_pending before its finally clears the
            # batch — dedupe by identity or the autoscaler sees 2x demand
            seen: set = set()
            for q in (self._pending, self._infeasible, self._scheduling_batch):
                for s in q:
                    if not s.resources or id(s) in seen:
                        continue
                    seen.add(id(s))
                    k = _shape_key_of(s)
                    parked[k] = parked.get(k, 0) + 1
            # specs in dispatched-but-unread pipelined rounds are demand too
            for specs in self._deferred_rounds.values():
                for s in specs:
                    if not s.resources or id(s) in seen:
                        continue
                    seen.add(id(s))
                    k = _shape_key_of(s)
                    deferred[k] = deferred.get(k, 0) + 1
            device_state = self._lazy_device._result
            ring_keys = (
                list(device_state.ring_keys())
                if device_state is not None
                else []
            )
            pg_bundles = [
                dict(b)
                for pg in self._pending_pgs
                if not pg.ready.is_set() and not pg.removed
                for b in pg.bundles
            ]
        # a shape both ring-parked and riding a deferred retry round is
        # ONE logical backlog seen from two tables — max() it instead of
        # summing, or the autoscaler provisions for phantom demand
        from ray_tpu.scheduler.elasticity import dedupe_task_shapes

        merged = dedupe_task_shapes(parked, deferred, ring_keys)
        out: List[Dict[str, float]] = []
        for key, n in merged.items():
            out.extend(dict(key) for _ in range(n))
        out.extend(pg_bundles)
        return out

    def _h_wait_actor(self, req: dict) -> ActorInfo:
        """Long-poll an actor's state: blocks server-side until it leaves
        PENDING/RESTARTING or the window closes (publisher.h actor-state
        channel analog; replaces 20 Hz GetActor polling from clients).
        An actor UNKNOWN at poll start is waited for within the window
        too: creations ride the pipelined client batch, so a fast caller
        (first method's direct-channel resolve) can legitimately long-poll
        before its creation message lands."""
        actor_id = req["actor_id"]
        deadline = time.monotonic() + min(float(req.get("timeout") or 2.0), 10.0)
        with self._cond:
            while True:
                info = self._actors.get(actor_id)
                if info is not None and info.state in ("ALIVE", "DEAD"):
                    return info
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    if info is None:
                        raise ValueError(f"unknown actor {actor_id}")
                    return info
                self._cond.wait(remaining)

    def _h_get_actor(self, req: dict) -> ActorInfo:
        actor_id = req.get("actor_id")
        if actor_id is None:
            name = req["name"]
            actor_id = self._named_actors.get(name)
            if actor_id is None:
                raise ValueError(f"no actor named {name!r}")
        info = self._actors.get(actor_id)
        if info is None:
            raise ValueError(f"unknown actor {actor_id}")
        return info

    # ------------------------------------------------------------------
    # owner liveness + fate-sharing (GcsJobManager / worker-failure
    # ownership analog): clients hold a session lease, heartbeat it on
    # the pipelined ClientBatch, and a crashed owner is fully reaped —
    # actors killed, worker leases revoked immediately, queued/in-flight
    # tasks cancelled, unproduced objects failed with OwnerDiedError.
    # ------------------------------------------------------------------
    def _h_client_hello(self, req: dict) -> dict:
        """Connection handshake: registers the owner session (when the
        caller runs one) and hands out the cluster epoch the client
        stamps its control stream with. Fence-exempt — this IS the
        owner-side resync protocol after a head restart."""
        cid = req.get("client_id")
        if cid and req.get("session") and cfg.owner_liveness:
            self._touch_owner(cid)
        return {
            "epoch": self.cluster_epoch,
            "owner_ttl_s": float(cfg.owner_lease_ttl_s),
            "owner_liveness": bool(cfg.owner_liveness),
        }

    def _touch_owner(self, cid: str) -> None:
        with self._lock:
            sess = self._owner_sessions.get(cid)
            if sess is None:
                sess = self._owner_sessions[cid] = {"last_strike": 0.0}
            sess["last"] = time.monotonic()
            sess["strikes"] = 0

    def _h_owner_beat(self, req: dict) -> None:
        """Owner session heartbeat (ClientBatch ``owner_beat``). Also the
        re-registration path after a head restart: the first beat the
        rebuilt head sees recreates the session."""
        cid = req.get("client_id")
        if cid and cfg.owner_liveness:
            self._touch_owner(cid)

    def _check_owner_liveness(self) -> None:
        """Strike-based owner death detection (same shape as the node
        health loop): an owner that misses ``owner_miss_threshold``
        consecutive windows of ``owner_lease_ttl_s`` is declared dead and
        fully reaped. One strike per window, not per poll."""
        if not cfg.owner_liveness:
            return
        ttl = max(0.1, float(cfg.owner_lease_ttl_s))
        threshold = max(1, int(cfg.owner_miss_threshold))
        now = time.monotonic()
        dead = []
        with self._lock:
            for cid, sess in self._owner_sessions.items():
                gap = now - sess.get("last", now)
                if gap <= ttl:
                    sess["strikes"] = 0
                    continue
                if now - sess.get("last_strike", 0.0) >= ttl * 0.9:
                    sess["strikes"] = sess.get("strikes", 0) + 1
                    sess["last_strike"] = now
                if sess.get("strikes", 0) >= threshold:
                    dead.append(cid)
        for cid in dead:
            logger.warning(
                "owner %s missed %d consecutive heartbeat windows; "
                "declaring it dead and reaping",
                cid[:8],
                threshold,
            )
            self._reap_owner(cid, crashed=True, reason="owner heartbeat lost")

    def _h_disconnect_client(self, req: dict) -> None:
        """A driver disconnected cleanly: reap its NON-detached actors
        (reference job-exit semantics — actors die with their owner
        unless lifetime="detached", actor.py:1875). Detached actors are
        owned by the head and only die on explicit kill."""
        cid = req.get("client_id")
        if not cid:
            return
        self._reap_owner(cid, crashed=False, reason="client disconnected")

    def _reap_owner(self, cid: str, crashed: bool, reason: str) -> None:
        """The full owner reap. Clean disconnects return worker leases
        and kill non-detached actors; a CRASHED owner additionally has
        its queued/in-flight tasks cancelled, its unproduced objects
        failed with OwnerDiedError (fate-sharing — dependents raise a
        typed error instead of hanging forever), and its holder counts
        dropped so produced objects it alone referenced are freed."""
        with self._lock:
            self._owner_sessions.pop(cid, None)
            victims = [
                info.actor_id
                for info in self._actors.values()
                if info.owner_client == cid
                and info.lifetime != "detached"
                and info.state != "DEAD"
            ]
            dead_leases = [
                (lid, e.get("node_id"))
                for lid, e in self._task_leases.items()
                if e.get("client_id") == cid
            ]
        # cached worker leases go back to their pools IMMEDIATELY — a
        # crashed owner's leases must not pin workers for 3x TTL
        for lid, node_id in dead_leases:
            with self._cond:
                if self._drop_task_lease_locked(lid) is not None:
                    key = (
                        "task_leases_revoked"
                        if crashed
                        else "task_leases_returned"
                    )
                    self.metrics[key] += 1
                    (TASK_LEASE_REVOKED if crashed else TASK_LEASE_RETURNED).inc()
                self._cond.notify_all()
            self._wal_flush()
            if node_id:
                self._agent_return_lease(node_id, lid)
        # reap OFF the handler thread: agent kill RPCs can block up to
        # their timeout per victim, while a disconnecting client only
        # waits ~5s for this reply
        for aid in victims:
            self._dispatch_pool.submit(
                _best_effort,
                self._h_kill_actor,
                {"actor_id": aid, "no_restart": True},
            )
        if crashed:
            self._fail_owner_work(cid)
        # produced objects fate-share through the refcount: the departed
        # owner's holds drop, freeing anything it alone referenced. Clean
        # disconnects normally release everything themselves first (then
        # this is a no-op), but a bounded exit drain may leave stragglers
        # — a client that is GONE can never send those releases later.
        self._drop_holder(cid)
        OWNERS_REAPED.inc(labels={"mode": "crash" if crashed else "disconnect"})
        if victims or dead_leases or crashed:
            logger.info(
                "owner %s reaped (%s): %d actors, %d worker leases",
                cid[:8],
                reason,
                len(victims),
                len(dead_leases),
            )

    def _fail_owner_work(self, cid: str) -> None:
        """Cancel a dead owner's queued and in-flight tasks and fail their
        return objects with OwnerDiedError."""
        from ray_tpu.core.object_store import OwnerDiedError

        doomed: List[LeaseRequest] = []
        in_flight: List[Tuple[str, str]] = []

        def _owned(s: LeaseRequest) -> bool:
            return s.client_id == cid and s.kind in ("task", "actor_method")

        with self._cond:
            for q in (self._pending, self._infeasible):
                kept = [s for s in q if not _owned(s)]
                doomed.extend(s for s in q if _owned(s))
                q.clear()
                q.extend(kept)
            for q in (
                self._scheduling_batch,
                # dispatched-but-uncompleted pipelined rounds: the
                # dispatch-time filter (and _fan_out_grants' unplaced
                # drop) honors the flag when the round completes
                *self._deferred_rounds.values(),
            ):
                for s in q:
                    # mid-schedule: flag for the dispatch-time filter
                    if _owned(s):
                        self._cancelled_leases.add(s.task_id)
                        doomed.append(s)
            for lid, (spec, nid) in list(self._in_flight.items()):
                if _owned(spec):
                    del self._in_flight[lid]
                    self._cancelled_leases.add(lid)
                    in_flight.append((lid, nid))
                    doomed.append(spec)
            self._cond.notify_all()
        for lid, nid in in_flight:
            client = self._clients.get(nid)
            if client is not None:
                self._dispatch_pool.submit(
                    _best_effort,
                    client.call,
                    "CancelLease",
                    {"task_id": lid, "force": False},
                )
        if not doomed:
            return
        err = OwnerDiedError(
            f"the owner of this object (client {cid[:8]}) died before the "
            "object was produced; objects fate-share with their owner"
        )
        ids = [oid for s in doomed for oid in s.return_ids]
        # keep_for_owner: the typed error must outlive the owner's holder
        # drop so dependents observe OwnerDiedError, not a generic
        # freed-object error
        self._seal_error_ids(ids, err, keep_for_owner=True)
        for s in doomed:
            if s.streaming:
                self._fail_stream(s, "owner died")
            self._release_lease_pins(s.task_id)
        logger.info(
            "owner %s: cancelled %d queued/in-flight tasks", cid[:8], len(doomed)
        )

    def _h_kill_actor(self, req: dict) -> None:
        info = self._actors.get(req["actor_id"])
        if info is None:
            return
        no_restart = req.get("no_restart", True)
        with self._lock:
            if no_restart:
                info.max_restarts = info.num_restarts  # exhaust the budget
            node_id = info.node_id
            client = self._clients.get(node_id) if node_id else None
        if client is not None:
            if no_restart:
                # permanent kill (the churn path, and what the pipelined
                # client batch carries): the actor id can never rebind to
                # a new worker, so the agent-side teardown can run off
                # this thread — a batched kill must not head-of-line
                # block the lease stream behind an agent round trip
                self._dispatch_pool.submit(
                    _best_effort,
                    client.call,
                    "KillActor",
                    {"actor_id": info.actor_id},
                )
            else:
                # restartable kill: the teardown must land BEFORE the
                # restart's creation lease can rebind this actor id on
                # the same agent, or a late KillActor would tear down
                # the replacement worker
                try:
                    client.call("KillActor", {"actor_id": info.actor_id})
                except RpcError:
                    pass
        self._restart_or_kill_actor(info, "killed by user")

    # ------------------------------------------------------------------
    # placement groups (GcsPlacementGroupManager/Scheduler analog, with the
    # batched bundle kernels + 2PC prepare/commit to agents)
    # ------------------------------------------------------------------
    def _h_create_pg(self, req: dict) -> dict:
        state = _PGState(
            pg_id=req.get("pg_id") or new_id(),
            bundles=[dict(b) for b in req["bundles"]],
            strategy=req.get("strategy", "PACK"),
            avoid_nodes=[str(n) for n in (req.get("avoid_nodes") or ())],
        )
        with self._cond:
            self._pgs[state.pg_id] = state
            self._pending_pgs.append(state)
            self._pgs_dirty = True
            self._cond.notify_all()
        return {"pg_id": state.pg_id}

    def _try_schedule_pgs(self) -> None:
        with self._lock:
            pending = list(self._pending_pgs)
            # consume the dirty bit: retry again only after the view changes
            # (node joins, reports, freed leases) — an unschedulable PG must
            # not busy-spin the scheduler thread.
            self._pgs_dirty = False
        for state in pending:
            if state.removed:
                with self._lock:
                    if state in self._pending_pgs:
                        self._pending_pgs.remove(state)
                continue
            if self._schedule_pg(state):
                with self._cond:
                    if state in self._pending_pgs:
                        self._pending_pgs.remove(state)
                    self._pending.extend(self._infeasible)
                    self._infeasible.clear()
                    self._cond.notify_all()

    def _schedule_pg(self, state: _PGState) -> bool:
        # device residency for the bundle packer too: when the scheduler
        # device is up, the PACK/SPREAD kernels read the RESIDENT arrays
        # (delta-synced dirty rows) instead of re-uploading a fresh host
        # copy of the cluster matrices per PG attempt. The capacity rows
        # beyond num_nodes are alive=False and score out of every kernel.
        # The refs are immutable jax values (nothing is donated), so
        # later rounds replacing device_state._avail can't invalidate a
        # pack in flight.
        device_state = self._lazy_device._result
        with self._lock:
            num_nodes = self.view.num_nodes
            any_alive = bool(self.view.alive.any())
            width = self.view.totals.shape[1]
            if device_state is not None and num_nodes > 0:
                device_state.sync(self.view)
                totals, avail, alive = device_state.resident_arrays()
            else:
                t0, a0, al0 = self.view.active_arrays()
                totals, avail, alive = t0.copy(), a0.copy(), al0.copy()
        if num_nodes == 0 or not any_alive:
            return False
        bundles = np.stack(
            [
                ResourceRequest.from_map(self.vocab, b).dense(width)
                for b in state.bundles
            ]
        )
        if state.avoid_nodes:
            from ray_tpu.scheduler.bundles import (
                schedule_bundles_soft_avoid,
            )

            # rows are resolved under a later lock window than the
            # arrays snapshot (and a client-supplied node id can intern
            # a fresh row past it) — the helper bounds-guards them
            with self._lock:
                rows_to_avoid = [
                    self.view.row_if_known(n) for n in state.avoid_nodes
                ]
            rows, success, _ = schedule_bundles_soft_avoid(
                totals, avail, alive, bundles, state.strategy,
                rows_to_avoid,
            )
        else:
            rows, success, _ = schedule_bundles(
                totals, avail, alive, bundles, state.strategy
            )
        if not success:
            return False
        chosen = [self.view.node_id(int(r)) for r in rows]
        # Pipelined 2PC (PrepareBundleResources/CommitBundleResources,
        # gcs_placement_group_scheduler.cc:192,219): prepares go out to
        # every involved agent CONCURRENTLY and the PG turns ready as soon
        # as the full quorum of prepare acks is in; commits are fired
        # asynchronously after that (agents admit leases against prepared
        # entries, so the commit flip is bookkeeping, not a gate). The old
        # serial prepare→serial commit chain cost one RPC round trip per
        # node per phase on the scheduler thread.
        by_node: Dict[str, List[int]] = {}
        for i, nid in enumerate(chosen):
            by_node.setdefault(nid, []).append(i)

        def prepare(nid: str, idxs: List[int]) -> bool:
            client = self._clients.get(nid)
            try:
                reply = client.call(
                    "PrepareBundles",
                    {
                        "pg_id": state.pg_id,
                        "bundles": {i: state.bundles[i] for i in idxs},
                    },
                )
                return bool(reply.get("ok"))
            except (RpcError, AttributeError):
                return False

        items = list(by_node.items())
        if len(items) == 1:
            acks = [prepare(*items[0])]
        else:
            futs = [
                self._dispatch_pool.submit(prepare, nid, idxs)
                for nid, idxs in items
            ]
            acks = [f.result() for f in futs]
        prepared = [nid for (nid, _), ack in zip(items, acks) if ack]
        if not all(acks):
            # rollback stays SYNCHRONOUS: a retry of this PG can start the
            # moment we return False, and a stale async rollback landing
            # after the retry's successful prepare would destroy the new
            # prepared entry on the agent (failure-path latency is free;
            # only the happy path needed pipelining)
            for nid in prepared:
                client = self._clients.get(nid)
                if client is not None:
                    _best_effort(
                        client.call,
                        "RollbackBundles",
                        {"pg_id": state.pg_id},
                    )
            return False
        for nid in prepared:
            client = self._clients.get(nid)
            if client is not None:
                self._dispatch_pool.submit(
                    _best_effort,
                    client.call,
                    "CommitBundles",
                    {"pg_id": state.pg_id},
                )
        with self._lock:
            for i, nid in enumerate(chosen):
                self.view.subtract(self.view.row_of(nid), bundles[i])
        state.node_per_bundle = chosen
        state.ready.set()
        return True

    def _h_wait_pg(self, req: dict) -> dict:
        state = self._pgs.get(req["pg_id"])
        if state is None:
            raise ValueError(f"unknown placement group {req['pg_id']}")
        t = req.get("timeout")
        ready = state.ready.wait(min(2.0 if t is None else t, 10.0))
        return {
            "ready": ready,
            "node_per_bundle": state.node_per_bundle if ready else [],
        }

    def _h_remove_pg(self, req: dict) -> None:
        state = self._pgs.get(req["pg_id"])
        if state is None:
            return
        state.removed = True
        involved = set(state.node_per_bundle)
        refund: Dict[str, np.ndarray] = {}
        if state.ready.is_set():
            with self._lock:
                width = self.view.active_arrays()[0].shape[1]
            for i, nid in enumerate(state.node_per_bundle):
                d = ResourceRequest.from_map(self.vocab, state.bundles[i]).dense(
                    width
                )
                refund[nid] = refund.get(nid, 0) + d
        for nid in involved:
            client = self._clients.get(nid)
            if client is None:
                continue
            try:
                client.call("ReturnBundles", {"pg_id": state.pg_id})
            except RpcError:
                continue
            with self._lock:
                node = self.nodes.get(nid)
                if nid in refund and node is not None and node.alive:
                    self.view.add(self.view.row_of(nid), refund[nid])

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def metrics_text(self) -> str:
        """The head scrape body (dashboard /metrics): dark-plane
        counters synced, the head's hand-counted table and cluster
        gauges published typed, the head's own registry merged into the
        federation (node="head", role="head", cumulative), and the whole
        federated registry — agents' and workers' shipped deltas
        included — rendered as one parser-valid exposition."""
        from ray_tpu.util.metrics import (
            registry_dump,
            sync_counter,
            sync_gauge,
        )

        try:
            from .event_loop import publish_dark_plane

            publish_dark_plane()
        except Exception:  # noqa: BLE001 - dark-plane sync is optional
            pass
        info = self._h_cluster_info(None)
        for name, value in info["metrics"].items():
            # the historical hand-rolled scrape names (ray_tpu_*) stay,
            # now typed through the registry instead of string-built
            sync_counter(
                f"ray_tpu_{name}", float(value),
                "Head lifecycle counter (HeadServer.metrics table).",
            )
        alive = sum(1 for n in info["nodes"] if n["Alive"])
        sync_gauge(
            "ray_tpu_nodes_alive", float(alive), "Live nodes in the view."
        )
        for n in info["nodes"]:
            for res, avail_v in (n["Available"] or {}).items():
                safe = (
                    res.replace("-", "_").replace(".", "_").replace("/", "_")
                )
                self._node_avail_gauge.set(
                    float(avail_v),
                    {"node": n["NodeID"], "resource": safe},
                )
        self.federation.apply("head", "head", registry_dump(), replace=True)
        return self.federation.text()

    def _dump_crash_bundle(self, reason: str) -> None:
        """Flight-recorder trigger (async: file I/O stays off whatever
        failure path tripped it; the recorder's own throttle bounds
        storms)."""
        if not cfg.crash_bundles:
            return
        from ray_tpu.util import flight_recorder

        if flight_recorder.throttled():
            return  # storm: don't even burn a pool slot
        try:
            self._dispatch_pool.submit(self._dump_crash_bundle_now, reason)
        except RuntimeError:  # pool shut down
            pass

    def _dump_crash_bundle_now(self, reason: str) -> Optional[str]:
        from ray_tpu.util import flight_recorder

        if flight_recorder.throttled():
            # re-checked here: the expensive QueryState snapshots below
            # must not run for a dump the recorder would discard
            return None
        try:
            state = {
                "summary": self._h_query_state({"kind": "summary"}),
                "sched": self._h_query_state({"kind": "sched"}),
            }
        except Exception:  # noqa: BLE001 - partial state beats none
            state = {}
        return flight_recorder.dump_bundle(
            reason,
            events=self.events,
            state=state,
            metrics_text=self.metrics_text,
            extra_meta={"epoch": self.cluster_epoch, "role": self.role},
        )

    def _h_cluster_info(self, req) -> dict:
        with self._lock:
            totals, avail, _ = self.view.active_arrays()
            busy_nodes = {nid for _, nid in self._in_flight.values()}
            for info in self._actors.values():
                if info.state == "ALIVE" and info.node_id:
                    busy_nodes.add(info.node_id)
            nodes = []
            for nid, n in self.nodes.items():
                row = self.view.row_of(nid) if n.alive else None
                nodes.append(
                    {
                        "NodeID": nid,
                        "Alive": n.alive,
                        "Address": n.address,
                        "Resources": dict(n.resources),
                        "Available": self.vocab.unpack(avail[row])
                        if row is not None
                        else {},
                        "Labels": dict(n.labels),
                        # zero-resource work keeps Available==Resources: the
                        # autoscaler needs a liveness signal beyond arithmetic
                        "Busy": nid in busy_nodes,
                    }
                )
        return {"nodes": nodes, "metrics": dict(self.metrics)}

    # ------------------------------------------------------------------
    # elastic-training gang membership (train/elastic.py rides these).
    # The head is the epoch AUTHORITY: the health loop's node-death
    # verdict bumps every gang with a member on the corpse, the owning
    # driver mirrors the epoch into the gang's rendezvous hub, and any
    # collective contribution stamped with a dead epoch is rejected at
    # the hub exactly like a stale control RPC at the cluster fence.
    # ------------------------------------------------------------------
    def _h_gang_register(self, req: dict) -> dict:
        gid = req["gang_id"]
        members = {int(r): str(n) for r, n in (req.get("members") or {}).items()}
        with self._cond:
            prev = self._gangs.get(gid)
            # monotone across generations AND head failovers: the owner
            # passes the last epoch it saw as a floor after re-connecting
            # to a promoted head that lost the (ephemeral) gang table
            floor = max(
                int(req.get("epoch_floor", 0)),
                prev["epoch"] if prev else 0,
            )
            epoch = floor + 1
            self._gangs[gid] = {
                "epoch": epoch,
                "owner": str(req.get("owner", "")),
                "members": members,
                "min_size": int(req.get("min_size", 1)),
                "dead_ranks": [],
                "updated": time.monotonic(),
                # unified elasticity plane (PR 19): the driver declares
                # its grow-back want so the controller can put the
                # gang's deficit into the demand matrix; world_hint is
                # the controller's last solver verdict (sustainable
                # world size), polled by the driver via GangHint. A
                # re-register (new generation) keeps no stale hint.
                "want_world": int(req.get("want_world", 0)),
                "resources_per_rank": dict(
                    req.get("resources_per_rank") or {}
                ),
                "grow": bool(req.get("grow", False)),
                "world_hint": None,
            }
            self._cond.notify_all()
        GANG_EPOCH_BUMPS.inc(labels={"reason": "register"})
        return {"epoch": epoch}

    def _h_gang_hint(self, req: dict) -> dict:
        """Driver poll of the elasticity controller's world-size verdict
        for one gang: ``{"world_hint": int|None, "epoch": int}``. None
        means the controller has not judged this gang (or is off) — the
        driver falls back to its legacy capacity probe."""
        with self._cond:
            g = self._gangs.get(req["gang_id"])
            if g is None:
                return {"world_hint": None, "epoch": 0}
            return {
                "world_hint": g.get("world_hint"),
                "epoch": g["epoch"],
            }

    def _h_gang_sync(self, req: dict) -> dict:
        """Long-poll the gang's membership epoch: returns immediately
        when the head's epoch differs from the caller's, else parks up
        to min(timeout, cfg.gang_sync_max_wait_s) on the head cond (the
        node-death bump notifies it)."""
        gid = req["gang_id"]
        known = int(req.get("epoch", -1))
        wait_s = min(
            float(req.get("timeout", 0.0)), float(cfg.gang_sync_max_wait_s)
        )
        deadline = time.monotonic() + max(0.0, wait_s)
        with self._cond:
            while True:
                g = self._gangs.get(gid)
                if g is None:
                    return {"epoch": 0, "members": {}, "dead_ranks": []}
                now = time.monotonic()
                if g["epoch"] != known or now >= deadline or self._shutdown:
                    return {
                        "epoch": g["epoch"],
                        "members": {
                            str(r): n for r, n in g["members"].items()
                        },
                        "dead_ranks": list(g["dead_ranks"]),
                    }
                self._cond.wait(timeout=min(1.0, deadline - now))

    def _h_gang_fence(self, req: dict) -> dict:
        """Owner-requested epoch bump: resize/grow decisions and actor-
        level deaths the driver observed before the health loop did."""
        gid = req["gang_id"]
        with self._cond:
            g = self._gangs.get(gid)
            if g is None:
                return {"epoch": 0}
            g["epoch"] += 1
            g["updated"] = time.monotonic()
            epoch = g["epoch"]
            self._cond.notify_all()
        GANG_EPOCH_BUMPS.inc(
            labels={"reason": str(req.get("reason", "fence"))}
        )
        return {"epoch": epoch}

    def _h_gang_unregister(self, req: dict) -> None:
        with self._cond:
            self._gangs.pop(req["gang_id"], None)
            self._cond.notify_all()

    def _gangs_note_node_death(self, node_id: str) -> None:
        """Health-loop feed into the membership protocol: any gang with
        a member on the dead node advances its epoch, so in-flight
        collectives of the dead generation are rejected as stale the
        moment the owner (or any rank) next touches the hub."""
        bumped = []
        with self._cond:
            for gid, g in self._gangs.items():
                dead = [
                    r for r, n in g["members"].items() if n == node_id
                ]
                if not dead:
                    continue
                g["epoch"] += 1
                g["updated"] = time.monotonic()
                seen = set(g["dead_ranks"])
                g["dead_ranks"].extend(
                    r for r in dead if r not in seen
                )
                bumped.append((gid, g["epoch"], dead))
            if bumped:
                self._cond.notify_all()
        for gid, epoch, dead in bumped:
            GANG_EPOCH_BUMPS.inc(labels={"reason": "node_death"})
            logger.warning(
                "gang %s: node %s died with rank(s) %s; epoch -> %d",
                gid,
                node_id,
                dead,
                epoch,
            )

    def _h_report_serve_state(self, req: dict) -> dict:
        with self._lock:
            self._serve_state[
                (req.get("client_id", ""), req.get("deployment", ""))
            ] = {"state": req.get("state") or {}, "ts": time.time()}
        return {"ok": True}

    # ------------------------------------------------------------------
    # router-fleet control plane (horizontally scaled ingress): the head
    # owns the tenant->router assignment table (epoch-fenced, WAL-
    # persisted) and the stream-lease checkpoints that make router
    # failover token-exact. Steady-state serving makes ZERO of these
    # calls — only membership changes, one batched checkpoint per
    # reconcile window per fleet, and budget reconciliation at
    # cfg.serve_budget_reconcile_s cadence touch the head.
    # ------------------------------------------------------------------
    def _serve_fence_locked(
        self, deployment: str, epoch: int
    ) -> Optional[dict]:
        """Assignment-epoch fence (caller holds self._lock): a control
        RPC stamped with a stale fleet epoch gets a typed stale reply —
        the sender was deposed and must refresh its assignment before
        touching stream leases or budgets again."""
        f = self._serve_fleets.get(deployment)
        cur = int(f["epoch"]) if f else 0
        if int(epoch) != cur:
            return {"stale": True, "epoch": cur}
        return None

    def _h_serve_fleet_join(self, req: dict) -> dict:
        dep = req["deployment"]
        rid = req["router_id"]
        with self._lock:
            f = self._serve_fleets.setdefault(
                dep, {"epoch": 0, "members": []}
            )
            if rid not in f["members"]:
                f["members"] = sorted(f["members"] + [rid])
                f["epoch"] = int(f["epoch"]) + 1
                self._wal(
                    (
                        "serve_fleet",
                        {
                            "deployment": dep,
                            "epoch": f["epoch"],
                            "members": list(f["members"]),
                        },
                    )
                )
            reply = {"epoch": f["epoch"], "members": list(f["members"])}
        self._wal_flush()
        return reply

    def _h_serve_fleet_leave(self, req: dict) -> dict:
        dep = req["deployment"]
        rid = req["router_id"]
        with self._lock:
            f = self._serve_fleets.setdefault(
                dep, {"epoch": 0, "members": []}
            )
            if rid in f["members"]:
                f["members"] = [m for m in f["members"] if m != rid]
                f["epoch"] = int(f["epoch"]) + 1
                self._wal(
                    (
                        "serve_fleet",
                        {
                            "deployment": dep,
                            "epoch": f["epoch"],
                            "members": list(f["members"]),
                        },
                    )
                )
            (self._serve_budget.get(dep) or {}).pop(rid, None)
            reply = {"epoch": f["epoch"], "members": list(f["members"])}
        self._wal_flush()
        return reply

    def _h_serve_assignment(self, req: dict) -> dict:
        with self._lock:
            f = self._serve_fleets.get(req["deployment"]) or {
                "epoch": 0,
                "members": [],
            }
            return {"epoch": f["epoch"], "members": list(f["members"])}

    def _h_serve_stream_acquire(self, req: dict) -> dict:
        dep = req["deployment"]
        with self._lock:
            stale = self._serve_fence_locked(dep, req.get("epoch", 0))
            if stale is not None:
                return stale
            sid = req["stream_id"]
            row = self._serve_streams.get(sid) or {
                "stream_id": sid,
                "deployment": dep,
                "tenant": req.get("tenant", "default"),
                "delivered": 0,
            }
            row["router_id"] = req["router_id"]
            row["delivered"] = max(
                int(row.get("delivered", 0)),
                int(req.get("delivered", 0)),
            )
            row["ts"] = time.time()
            self._serve_streams[sid] = row
            self._wal(("serve_stream", dict(row)))
            reply = {"row": dict(row)}
        self._wal_flush()
        return reply

    def _h_serve_stream_ckpt(self, req: dict) -> dict:
        dep = req["deployment"]
        rid = req["router_id"]
        with self._lock:
            stale = self._serve_fence_locked(dep, req.get("epoch", 0))
            if stale is not None:
                return stale
            applied = 0
            for sid, delivered in (req.get("ckpts") or {}).items():
                row = self._serve_streams.get(sid)
                if row is None or row.get("router_id") != rid:
                    # the stream moved to a sibling after this batch was
                    # cut: its checkpoint is stale, drop it
                    continue
                nxt = max(int(row.get("delivered", 0)), int(delivered))
                if nxt == row.get("delivered"):
                    continue
                row["delivered"] = nxt
                row["ts"] = time.time()
                # one WAL record per stream id: the replication layer
                # shards records by stream_id, a batched record could
                # not be routed to owner shards
                self._wal(
                    (
                        "serve_stream_ckpt",
                        {
                            "stream_id": sid,
                            "delivered": nxt,
                            "router_id": rid,
                        },
                    )
                )
                applied += 1
            reply = {"ok": True, "applied": applied}
        self._wal_flush()
        return reply

    def _h_serve_stream_release(self, req: dict) -> dict:
        with self._lock:
            dropped = 0
            for sid in req.get("stream_ids") or ():
                if self._serve_streams.pop(sid, None) is not None:
                    self._wal(("serve_stream_gone", sid))
                    dropped += 1
            reply = {"ok": True, "dropped": dropped}
        self._wal_flush()
        return reply

    def _h_serve_stream_lookup(self, req: dict) -> dict:
        with self._lock:
            row = self._serve_streams.get(req.get("stream_id", ""))
            return {"row": dict(row) if row else None}

    def _h_serve_budget(self, req: dict) -> dict:
        """Budget reconciliation: fold this router's per-tenant usage/
        demand report in, prune stale or deposed reporters, and hand
        back its share of the GLOBAL admission rate (∝ summed WFQ
        weights of its active tenants) plus the cluster-headroom bit
        that fixes shed retry hints."""
        from ray_tpu.serve.fleet import compute_budget_shares

        dep = req["deployment"]
        rid = req["router_id"]
        window = max(0.05, float(cfg.serve_budget_reconcile_s))
        with self._lock:
            stale = self._serve_fence_locked(dep, req.get("epoch", 0))
            if stale is not None:
                return stale
            members = set(
                (self._serve_fleets.get(dep) or {}).get("members", ())
            )
            reports = self._serve_budget.setdefault(dep, {})
            reports[rid] = {
                "usage": dict(req.get("usage") or {}),
                "waiting": dict(req.get("waiting") or {}),
                "weights": dict(req.get("weights") or {}),
                "pressure": dict(req.get("pressure") or {}),
                "ts": time.monotonic(),
            }
            now = time.monotonic()
            for other in list(reports):
                if other not in members or now - reports[other][
                    "ts"
                ] > max(3.0, 4 * window):
                    del reports[other]
            shares = compute_budget_shares(
                reports,
                float(cfg.serve_admission_qps),
                float(cfg.serve_admission_burst),
                window,
            )
            share = shares.get(rid) or {
                "rate": 0.0,
                "burst": float(cfg.serve_admission_burst),
                "headroom": True,
            }
            # serve pressure → scheduler demand rows (PR 18): fold the
            # fleet's queued prefill tokens through the autoscaler
            # kernel against the alive nodes' residual CPU rows; the
            # hint rides the reply back to the fleet's SLO autoscaler
            avail = [
                float((n.resources or {}).get("CPU", 0.0))
                for n in self.nodes.values()
                if getattr(n, "alive", True)
            ]
            snapshot = {r: dict(rep) for r, rep in reports.items()}
        hint = None
        # unified elasticity plane (PR 19): when the controller is on
        # and has a fresh solver verdict for this deployment, it IS the
        # capacity hint — one solve sized serve, gangs, and tasks
        # together, so the one-shot plan below would just disagree with
        # what the fleet was actually granted.
        if cfg.elastic_controller:
            with self._lock:
                row = self._serve_capacity_hints.get(dep)
            if (
                row is not None
                and (row.get("hint") or {}).get("source")
                == "elastic_controller"
                and time.monotonic() - row.get("ts", 0.0)
                <= max(3.0, 4 * float(cfg.elastic_tick_s))
            ):
                hint = dict(row["hint"])
        if hint is None:
            try:
                from ray_tpu.scheduler.serve_demand import (
                    capacity_plan,
                    pressure_rollup,
                )

                pressure = pressure_rollup(snapshot)
                if pressure:
                    hint = capacity_plan(avail, pressure)
            except Exception:  # noqa: BLE001 - hint is advisory
                hint = None
            with self._lock:
                self._serve_capacity_hints[dep] = {
                    "hint": hint,
                    "ts": time.monotonic(),
                }
        # the hint key is ALWAYS present — a None is the positive
        # "demand drained" signal that lets the fleet clear its
        # hold-capacity latch immediately instead of waiting out the
        # staleness window (hold-capacity latch fix)
        reply = {**share, "window_s": window}
        reply["capacity_hint"] = hint
        return reply

    # -- weights-version epochs (online-RL two-phase publish fence) -------

    def _replay_weights_epoch(self, row: dict) -> None:
        """Apply one ``weights_epoch`` WAL record (seal or commit phase).
        Shared by replay-after-restart and the standby's replication
        apply path — both must converge on the leader's exact state."""
        dep = row["deployment"]
        w = self._weights_epochs.setdefault(
            dep, {"committed": 0, "meta": {}, "sealed": None}
        )
        if row.get("phase") == "seal":
            w["sealed"] = {
                "epoch": int(row["epoch"]),
                "meta": dict(row.get("meta", {})),
            }
        else:  # commit
            w["committed"] = int(row["epoch"])
            w["meta"] = dict(row.get("meta", {}))
            w["sealed"] = None

    def _h_weights_publish_seal(self, req: dict) -> dict:
        """Phase 1 of a weights publish: reserve committed+1 and WAL the
        seal. A re-seal (publisher retrying after a head death) simply
        supersedes any dangling sealed phase — only a commit that names
        the currently sealed epoch lands, so the fence can never tear."""
        dep = req["deployment"]
        with self._lock:
            w = self._weights_epochs.setdefault(
                dep, {"committed": 0, "meta": {}, "sealed": None}
            )
            epoch = int(w["committed"]) + 1
            meta = dict(req.get("meta") or {})
            w["sealed"] = {"epoch": epoch, "meta": meta}
            self._wal(
                (
                    "weights_epoch",
                    {
                        "deployment": dep,
                        "phase": "seal",
                        "epoch": epoch,
                        "meta": meta,
                    },
                )
            )
            reply = {"epoch": epoch, "committed": int(w["committed"])}
        self._wal_flush()
        return reply

    def _h_weights_publish_commit(self, req: dict) -> dict:
        """Phase 2: flip the sealed epoch to committed. Stale-fenced like
        gang epochs — a commit for anything other than the currently
        sealed epoch is rejected so a deposed publisher (or a retry that
        raced a newer seal) can never clobber the fence."""
        dep = req["deployment"]
        epoch = int(req["epoch"])
        with self._lock:
            w = self._weights_epochs.setdefault(
                dep, {"committed": 0, "meta": {}, "sealed": None}
            )
            sealed = w.get("sealed")
            if int(w["committed"]) >= epoch:
                # idempotent re-commit after a lost reply
                reply = {"committed": int(w["committed"]), "stale": False}
            elif sealed is None or int(sealed["epoch"]) != epoch:
                reply = {"committed": int(w["committed"]), "stale": True}
            else:
                w["committed"] = epoch
                w["meta"] = dict(sealed.get("meta", {}))
                w["sealed"] = None
                self._wal(
                    (
                        "weights_epoch",
                        {
                            "deployment": dep,
                            "phase": "commit",
                            "epoch": epoch,
                            "meta": w["meta"],
                        },
                    )
                )
                reply = {"committed": epoch, "stale": False}
        self._wal_flush()
        return reply

    def _h_weights_epoch_get(self, req: dict) -> dict:
        with self._lock:
            w = self._weights_epochs.get(req["deployment"])
            if w is None:
                return {"committed": 0, "meta": {}, "sealed": None}
            return {
                "committed": int(w["committed"]),
                "meta": dict(w.get("meta", {})),
                "sealed": dict(w["sealed"]) if w.get("sealed") else None,
            }

    def _h_query_state(self, req: dict) -> Any:
        kind = req.get("kind", "summary")
        if kind == "explain_placement":
            # scheduler decision attribution (ISSUE 15): the five
            # per-term cost contributions of one task's winning placement
            return self.explain_placement(req.get("task_id", ""))
        if kind == "metrics_text":
            # the federated scrape body over RPC (dashboard-less tests,
            # remote bundle collection)
            return self.metrics_text()
        if kind == "rpc_handlers":
            # per-handler timing (instrumented_io_context stats analog)
            from .rpc import HANDLER_STATS

            return HANDLER_STATS.snapshot()
        if kind == "object_plane":
            # cross-node transport: peer-link table occupancy + grant/
            # revoke lifecycle counts and the head-process transfer
            # counters (agents expose their own via DebugState "net")
            from .object_plane import (
                OBJECT_TRANSFER_BYTES,
                PEER_CONN_REUSED,
                TRANSFER_STRIPE_MS,
            )

            with self._lock:
                links = [
                    self._peer_link_row(e)
                    for e in self._peer_links.values()
                ]
            return {
                "peer_links": links,
                "peer_link_count": len(links),
                "peer_links_granted": self.metrics["peer_links_granted"],
                "peer_links_revoked": self.metrics["peer_links_revoked"],
                "peer_links_reused": int(PEER_CONN_REUSED.value()),
                "transfer_bytes": {
                    path: int(OBJECT_TRANSFER_BYTES.value({"path": path}))
                    for path in ("shm", "inline", "rpc", "socket")
                },
                "transfer_stripe_ms": TRANSFER_STRIPE_MS.summary(),
            }
        if kind == "gangs":
            # elastic-training membership: epoch + member map per gang
            with self._lock:
                return {
                    gid: {
                        "epoch": g["epoch"],
                        "owner": g["owner"],
                        "members": {
                            str(r): n for r, n in g["members"].items()
                        },
                        "min_size": g["min_size"],
                        "dead_ranks": list(g["dead_ranks"]),
                        "want_world": g.get("want_world", 0),
                        "grow": g.get("grow", False),
                        "world_hint": g.get("world_hint"),
                    }
                    for gid, g in self._gangs.items()
                }
        if kind == "weights_epochs":
            # online-RL publish fence: committed epoch + any in-flight
            # sealed phase per deployment
            with self._lock:
                return {
                    dep: {
                        "committed": int(w["committed"]),
                        "meta": dict(w.get("meta", {})),
                        "sealed": dict(w["sealed"])
                        if w.get("sealed")
                        else None,
                    }
                    for dep, w in self._weights_epochs.items()
                }
        if kind == "elasticity":
            # unified elasticity plane (PR 19): tick latency
            # percentiles, last actuation plan, drain table
            ctrl = getattr(self, "_elasticity", None)
            if ctrl is None:
                return {"enabled": False}
            state = ctrl.state()
            state["enabled"] = bool(cfg.elastic_controller)
            with self._lock:
                state["draining_nodes"] = {
                    n: round(d - time.monotonic(), 2)
                    for n, d in self._draining_nodes.items()
                }
            return state
        if kind == "replication":
            # replicated control plane: role, shipping stream position,
            # per-standby follower lag, owner-shard occupancy, pending
            # revocation fan-outs
            repl = self._repl.state()
            with self._lock:
                shards = {
                    "objects": self._objects.shard_sizes(),
                    "task_leases": self._task_leases.shard_sizes(),
                    "peer_links": self._peer_links.shard_sizes(),
                }
                pending_revokes = len(self._pending_revokes)
            from .replication import FAILOVER_MS

            return {
                "role": self.role,
                "epoch": self.cluster_epoch,
                "fenced": self._fenced,
                "leader_hint": self._leader_hint,
                "last_shipped_seq": repl["seq"],
                "ring_records": repl["ring_records"],
                "standbys": repl["standbys"],
                "follower_lag_records": max(
                    (s["lag_records"] for s in repl["standbys"]),
                    default=0,
                ),
                "shards": shards,
                "pending_revokes": pending_revokes,
                "failover_ms": FAILOVER_MS.summary(),
            }
        if kind == "hotpath":
            # execution-plane hot path: framing-path selection + native
            # vs fallback counters, fused-event-loop occupancy, ring
            # fill levels, live pipelines, dispatch decomposition — the
            # head process's own view (owners/agents expose theirs via
            # the agent DebugState "hotpath" block)
            from .event_loop import hotpath_state

            return hotpath_state()
        with self._lock:
            if kind == "actors":
                return [dict(vars(a)) for a in self._actors.values()]
            if kind == "objects":
                return [
                    {
                        "object_id": oid,
                        "sealed": e.event.is_set(),
                        "size": e.size,
                        "locations": sorted(e.locations),
                        "error": e.error is not None,
                    }
                    for oid, e in self._objects.items()
                ]
            if kind == "placement_groups":
                return [
                    {
                        "pg_id": p.pg_id,
                        "strategy": p.strategy,
                        "ready": p.ready.is_set(),
                        "bundles": p.bundles,
                        "nodes": p.node_per_bundle,
                    }
                    for p in self._pgs.values()
                ]
            if kind == "leases":
                return {
                    "pending": len(self._pending),
                    "infeasible": len(self._infeasible),
                    "in_flight": len(self._in_flight),
                }
            if kind == "sched":
                # the scheduling plane: round-latency decomposition,
                # pipeline occupancy, delta-sync and parked-ring state,
                # multi-objective weights + starvation/preemption state,
                # and the autoscaler solver's health — observable without
                # a bench run
                from ray_tpu.scheduler.binpack import (
                    SOLVER_FALLBACKS,
                    SOLVER_ITERS,
                    SOLVER_RUNS,
                )
                from ray_tpu.scheduler.device import score_weights_from_cfg

                ds = self._lazy_device._result
                return {
                    "pipeline_enabled": bool(cfg.sched_pipeline),
                    "pipeline": (
                        self._pipeline.stats()
                        if self._pipeline is not None
                        else None
                    ),
                    "rounds_deferred": len(self._deferred_rounds),
                    "round_ms": SCHED_ROUND_MS.summary(),
                    "upload_ms": SCHED_UPLOAD_MS.summary(),
                    "kernel_ms": SCHED_KERNEL_MS.summary(),
                    "readback_ms": SCHED_READBACK_MS.summary(),
                    # device stats carry the delta-sync counters incl.
                    # delta_rows_hwm (largest single dirty-row push)
                    "device": dict(ds.stats) if ds is not None else None,
                    "delta_rows_hwm": (
                        ds.stats.get("delta_rows_hwm", 0)
                        if ds is not None
                        else 0
                    ),
                    "ring_occupancy": (
                        ds.ring_occupancy() if ds is not None else 0
                    ),
                    "ring_slots": ds.ring_slots if ds is not None else 0,
                    "unparked_via_ring": self.metrics.get(
                        "leases_unparked_ring", 0
                    ),
                    "sched_rounds": self.metrics["sched_rounds"],
                    "score_weights": tuple(score_weights_from_cfg()),
                    "shape_wait_max_rounds": (
                        max(self._shape_wait.values())
                        if self._shape_wait
                        else 0
                    ),
                    "shapes_waiting": len(self._shape_wait),
                    "preempt_nominations": self.metrics[
                        "preempt_nominations"
                    ],
                    "preemptions": self.metrics["preemptions"],
                    "preemptions_by_kind": (
                        SCHED_PREEMPTIONS.values_by_label()
                    ),
                    # locality-scored placement: hit_frac_sum / scored ==
                    # the shuffle plane's locality hit-rate
                    "locality": {
                        "scored": SCHED_LOCALITY_SCORED.value(),
                        "hit_frac_sum": round(
                            SCHED_LOCALITY_HIT_FRAC.value(), 3
                        ),
                    },
                    "autoscaler_solver": {
                        "runs": SOLVER_RUNS.value(),
                        "fallbacks": SOLVER_FALLBACKS.value(),
                        "iters_per_solve": SOLVER_ITERS.value(),
                    },
                }
            if kind == "serve":
                # the serving plane, as last reported by each ingress
                # router: replica tables, lease-hit and prefix-cache hit
                # rates, admission/shed counters, latency summaries
                now = time.time()
                deployments = {}
                for (cid, dep), entry in list(self._serve_state.items()):
                    if now - entry["ts"] > 30.0:
                        del self._serve_state[(cid, dep)]
                        continue
                    blob = dict(entry["state"])
                    blob["reporter"] = cid
                    blob["age_s"] = round(now - entry["ts"], 2)
                    deployments[dep] = blob
                return {
                    "deployments": deployments,
                    # router-fleet assignment tables: epoch + member
                    # list per deployment (the ring derives from these)
                    "fleets": {
                        dep: dict(f)
                        for dep, f in self._serve_fleets.items()
                    },
                    "stream_leases": len(self._serve_streams),
                    # per-tenant serve pressure (queued prefill tokens)
                    # as last reported through the budget RPCs, plus the
                    # scheduler kernel's capacity verdict on it
                    "pressure": {
                        dep: {
                            rid: dict(rep.get("pressure") or {})
                            for rid, rep in reports.items()
                        }
                        for dep, reports in self._serve_budget.items()
                    },
                    # hint timestamps are monotonic (stored at budget
                    # reconcile time), so age against the same clock
                    "capacity_hints": {
                        dep: entry.get("hint")
                        for dep, entry in (
                            self._serve_capacity_hints.items()
                        )
                        if time.monotonic() - entry.get("ts", 0) < 30.0
                    },
                }
            if kind == "dispatch":
                # the task-lease dispatch plane (lease-cached direct
                # dispatch): active leases + per-owner counts + lifecycle
                per_owner: Dict[str, int] = {}
                for e in self._task_leases.values():
                    per_owner[e["client_id"]] = (
                        per_owner.get(e["client_id"], 0) + 1
                    )
                return {
                    "task_leases": [
                        {
                            "lease_id": e["lease_id"],
                            "state": e["state"],
                            "client_id": e["client_id"],
                            "node_id": e["node_id"],
                            "fn_id": e["fn_id"],
                            "resources": dict(e["resources"]),
                        }
                        for e in self._task_leases.values()
                    ],
                    "per_owner": per_owner,
                    "granted": self.metrics["task_leases_granted"],
                    "returned": self.metrics["task_leases_returned"],
                    "revoked": self.metrics["task_leases_revoked"],
                }
            return {
                "metrics": dict(self.metrics),
                "num_nodes": sum(1 for n in self.nodes.values() if n.alive),
                "num_actors": len(self._actors),
                "num_objects": len(self._objects),
            }

    def shutdown(self, stop_agents: bool = True) -> None:
        """Stop the head. With ``stop_agents=False`` the agents (and their
        actors) keep running — the head-restart recovery path."""
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()
        if getattr(self, "_elasticity", None) is not None:
            self._elasticity.stop()
        self._repl.stop()
        if self._pipeline is not None:
            # drain in-flight rounds (their grants are already paid for on
            # the device mirror) before tearing the completion thread down
            self._pipeline.flush(timeout=5.0)
            self._pipeline.stop()
        if self._persist_path:
            # UNCONDITIONAL final snapshot: hot-path dirtying is rate-gated
            # (_mark_hot_dirty), so the dirty bit alone can't prove the
            # last snapshot is current — a clean shutdown must never lose
            # the gate window
            self._persist_dirty = False
            self._persist_now()
        self.jobs.shutdown()
        if self.dashboard is not None:
            self.dashboard.stop()
        with self._lock:
            clients = list(self._clients.values())
        if stop_agents:
            for client in clients:
                try:
                    client.call("Shutdown", timeout=1.0)
                except RpcError:
                    pass
        # close channels AND unregister this head's breaker callbacks: a
        # successor head (restart_head keeps both in-process for a moment)
        # must not see stale unreachable-callbacks fire into dead state
        for client in clients:
            _best_effort(client.close)
        self._dispatch_pool.shutdown(wait=False, cancel_futures=True)
        self._server.stop()


def main() -> None:  # pragma: no cover - exercised via subprocess in tests
    import argparse

    parser = argparse.ArgumentParser(description="ray_tpu head server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=6380)
    parser.add_argument("--dashboard-port", type=int, default=8265)
    parser.add_argument("--no-dashboard", action="store_true")
    parser.add_argument(
        "--device-scheduler",
        default=None,
        action=argparse.BooleanOptionalAction,
        help="XLA kernel scheduler (default on; --no-device-scheduler for "
        "the NumPy golden model)",
    )
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    head = HeadServer(
        host=args.host,
        port=args.port,
        use_device_scheduler=args.device_scheduler,
        dashboard_port=None if args.no_dashboard else args.dashboard_port,
    )
    print(f"ray_tpu head listening on {head.address}", flush=True)
    if head.dashboard is not None:
        print(
            f"dashboard at http://{args.host}:{head.dashboard.port}", flush=True
        )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        head.shutdown()


if __name__ == "__main__":
    main()
