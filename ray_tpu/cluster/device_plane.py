"""Device-direct data plane: seal, ship, and land device-resident
tensors without the host bounce.

Every cross-node move of a ``jax.Array`` used to pay
HBM→host-pickle-copy→arena→socket→arena→host-copy→HBM: cloudpickle's
default jax reducer materializes a FULL host copy of the tensor inside
the pickle pass, and the receive side reconstructs another host copy
before ``device_put``. This module removes both copies by teaching the
RTP5 wire format (``cluster/serialization.py``) about **device
frames**:

- **Seal side** — :class:`DeviceAwarePickler` intercepts sealable
  ``jax.Array`` leaves in ``reducer_override`` and reduces them to
  ``(_land_device_leaf, (meta, PickleBuffer(view)))`` where ``view`` is
  a dlpack/``__array__`` export of the device buffer. On the CPU
  backend the exported pointer IS the device buffer (zero-copy — the
  tier-1-testable path); on accelerator backends the export is one
  bounded D2H readout, chunked through :class:`DeviceChunkPump` so the
  readout overlaps with the arena write / ``sendmsg`` stripes instead
  of materializing the whole tensor first. The PickleBuffer rides the
  existing out-of-band frame machinery, so arena puts scatter-gather
  the device bytes with ONE copy and socket sends gather them straight
  from the arena.
- **Land side** — ``_land_device_leaf`` is an ordinary module function
  referenced from the pickle stream, so every transport that carries
  RTP5 frames (shm views, socket stripes, chunked RPC, spill files)
  lands device frames with no format change and no version bump: the
  degradation ladder device-frame → host-arena → chunked-RPC is the
  ladder the object plane already has. Landing honours the process's
  :func:`landing` mode: ``"device"`` (default) issues ``device_put``
  straight from the arriving buffer (arena view / socket landing zone —
  no intermediate host copy); ``"host"`` returns the read-only host
  view for consumers that re-export (servers, spill).
- **Overlap** — :class:`DeviceLandingZone` wraps a staged arena entry
  on the socket receive path (``fetch_to_store(land="device")``): as
  disjoint stripes land, completed chunks of the contiguous prefix are
  ``device_put`` in flight, overlapping H2D with the remaining recv.
  Aborts drop the partial device buffers AND the staged pages
  (``abort_put``), and per-stripe retry/resume still works because the
  zone only consumes contiguous-prefix bytes.

Kill switch: ``RAY_TPU_DEVICE_PLANE=0`` disables frame interception and
landing zones everywhere; sealed device frames remain loadable (the
land function stays importable) and land host-side. The seam —
descriptor here, D2H/H2D pump here + transport.py, landing in
shm_store/net — is deliberately the shape a future RDMA/dmabuf backend
swaps into: replace the export/landing pair, keep the frame format.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# hot-path counters (plain-int increments, wire.py contract: rate
# indicators whose flat-vs-nonzero proof is race-safe)
# ---------------------------------------------------------------------------
_stats = {
    "device_frame_seals_total": 0,  # jax leaves sealed as device frames
    "device_frame_zero_copy_total": 0,  # of which the export aliased HBM
    "device_frame_lands_total": 0,  # leaves landed (any mode)
    "device_frame_lands_device_total": 0,  # of which landed on-device
    "device_frame_bytes_total": 0,  # payload bytes moved as device frames
    "device_pump_chunks_total": 0,  # chunked D2H pump chunks drained
    "device_land_chunks_total": 0,  # landing-zone H2D chunks issued
}


def device_stats() -> dict:
    return dict(_stats)


def publish_device_metrics() -> dict:
    """Sync the hot-path counters into the metrics registry (called from
    observability surfaces, never the data path itself)."""
    from ray_tpu.util.metrics import sync_counter

    for name, v in _stats.items():
        sync_counter(name, v, "Device-direct data plane frame events.")
    return device_stats()


def device_plane_enabled() -> bool:
    """Kill switch (RAY_TPU_DEVICE_PLANE, read live) AND jax present."""
    try:
        from ray_tpu.config import cfg

        if not cfg.device_plane:
            return False
    except Exception:  # noqa: BLE001 - config unavailable (bootstrap)
        import os

        if os.environ.get("RAY_TPU_DEVICE_PLANE", "1").lower() in (
            "0",
            "false",
            "no",
        ):
            return False
    return _jax() is not None


def _jax():
    """jax, or None — cached per process (import is the expensive bit)."""
    global _JAX, _JAX_TRIED
    if not _JAX_TRIED:
        _JAX_TRIED = True
        try:
            import jax as _j

            _JAX = _j
        except ImportError:
            _JAX = None
    return _JAX


_JAX = None
_JAX_TRIED = False


# ---------------------------------------------------------------------------
# sealability + export
# ---------------------------------------------------------------------------


def is_device_array(value: Any) -> bool:
    jax = _jax()
    return jax is not None and isinstance(value, jax.Array)


def is_sealable_device_array(value: Any) -> bool:
    """True when ``value`` is a concrete single-shard ``jax.Array`` the
    device plane can export as one frame. Tracers, multi-device-sharded
    and non-addressable arrays fall through to jax's own reducer (which
    understands shardings) — the plane never changes semantics, only
    the copy count."""
    jax = _jax()
    if jax is None or not isinstance(value, jax.Array):
        return False
    if isinstance(value, jax.core.Tracer):
        return False
    try:
        if not value.is_fully_addressable:
            return False
        if len(value.sharding.device_set) != 1:
            return False
        if value.size == 0:
            return False  # jax's own path; nothing to win on 0 bytes
    except Exception:  # noqa: BLE001 - deleted/donated buffer
        return False
    return True


def resolve_dtype(name: str) -> np.dtype:
    """dtype by NAME, resolving ml_dtypes extension types (bfloat16,
    float8_*) that have no loadable numpy ``.str`` form."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def export_device_view(arr) -> Tuple[np.ndarray, bool]:
    """``(host_ndarray, zero_copy)`` for a sealable device array.

    dlpack first: on the CPU backend the exported pointer IS the device
    buffer, so the seal is genuinely zero-copy. Extension dtypes
    (bfloat16, float8) and backends whose buffers are not
    host-addressable fall back to ``__array__`` (one D2H readout). The
    returned ndarray keeps the device buffer alive (dlpack capsule /
    jax's cached host value), which is exactly the lifetime the seal's
    gather-copy needs."""
    try:
        host = np.from_dlpack(arr)
        zero_copy = True
    except Exception:  # noqa: BLE001 - dtype/backend without dlpack
        host = np.asarray(arr)
        zero_copy = False
        try:
            # jax CPU arrays alias through __array__ too — detect so the
            # zero-copy counter reflects what actually happened
            zero_copy = (
                host.ctypes.data == arr.unsafe_buffer_pointer()
            )
        except Exception:  # noqa: BLE001 - backend without raw pointers
            pass
    if not host.flags.c_contiguous:
        host = np.ascontiguousarray(host)
        zero_copy = False
    return host, zero_copy


# ---------------------------------------------------------------------------
# landing mode (thread-local: fetch paths scope it around deserialize)
# ---------------------------------------------------------------------------

_LANDING = threading.local()


def landing_mode() -> str:
    return getattr(_LANDING, "mode", "device")


def landing_requested() -> bool:
    """True only while an explicit ``landing("device")`` scope is active
    on this thread — the scope-less default ("device") does NOT count.
    Transport callers use this to decide whether a socket get should pay
    for a :class:`DeviceLandingZone`: only consumers that declared the
    payload tensor-heavy opt in (rdt pulls, elastic ``fetch_sealed``);
    a generic get must not ``device_put`` an arbitrary pickled object's
    raw byte stream — headers, pickle opcodes, multi-GB non-tensor
    payloads — into HBM just to discard the chunks after ``finish()``."""
    return getattr(_LANDING, "mode", None) == "device"


@contextlib.contextmanager
def landing(mode: str):
    """Scope the device-frame landing mode for deserialization on this
    thread: ``"device"`` (default) lands leaves as ``jax.Array`` via
    ``device_put`` straight from the arriving buffer; ``"host"`` returns
    read-only host views (consumers that re-export or run jax-free)."""
    if mode not in ("device", "host"):
        raise ValueError(f"unknown landing mode {mode!r}")
    prev = getattr(_LANDING, "mode", None)
    _LANDING.mode = mode
    try:
        yield
    finally:
        if prev is None:
            del _LANDING.mode
        else:
            _LANDING.mode = prev


def _land_device_leaf(meta: dict, buf) -> Any:
    """Reconstruct one device frame. Referenced BY NAME from pickle
    streams — its module path is wire format; do not move or rename.

    ``buf`` arrives as a zero-copy memoryview slice of the incoming
    frame (PEP 574), an arena view, or in-band bytes. Device landing is
    ONE ``device_put`` from that buffer — the only host→device hop; no
    intermediate host copy ever exists on this path."""
    host = np.frombuffer(buf, dtype=resolve_dtype(meta["d"])).reshape(
        meta["s"]
    )
    _stats["device_frame_lands_total"] += 1
    _stats["device_frame_bytes_total"] += host.nbytes
    try:
        from ray_tpu.cluster.object_plane import OBJECT_TRANSFER_BYTES

        OBJECT_TRANSFER_BYTES.inc(host.nbytes, labels={"path": "device"})
    except Exception:  # noqa: BLE001 - metrics are optional at land time
        pass
    jax = _jax()
    # the kill switch disables DEVICE behavior end to end: with the
    # plane off, frames sealed earlier (or by a peer with it on) still
    # load, but land host-side
    if jax is None or landing_mode() == "host" or not device_plane_enabled():
        return host  # read-only view over the backing buffer
    _stats["device_frame_lands_device_total"] += 1
    out = jax.device_put(host)
    # jax's transfer machinery keeps the device_put SOURCE alive until
    # the copy is marked complete and a later dispatch drains the
    # keepalive; here that source is a view over the incoming frame
    # (often an arena page), so without an explicit flush the pin
    # outlives the deserialize and a concurrent delete zombies the page.
    # Queue the landed array for flush_landing_keepalive (wire.loads
    # calls it once per deserialize) — queuing, not blocking here, keeps
    # H2D transfers of sibling leaves overlapped.
    pending = getattr(_LANDING, "pending", None)
    if pending is None:
        pending = _LANDING.pending = []
    pending.append(out)
    return out


_FLUSH_SRC = np.zeros(1, dtype=np.uint8)


def _drain_transfer_keepalive(jax) -> None:
    """One trivial dispatch to drain jax's transfer-keepalive queue
    (entries only release on a dispatch AFTER their transfer completes).
    The dispatch's own source is the module-level constant, so it takes
    over the keepalive slot and pins nothing."""
    try:
        jax.device_put(_FLUSH_SRC)
    except Exception:  # noqa: BLE001 - backend torn down mid-shutdown
        pass


def flush_landing_keepalive() -> None:
    """Release jax's keepalive refs on this deserialize's view-backed
    ``device_put`` sources: block until every landed array's transfer is
    marked complete, then issue one trivial dispatch to drain the
    keepalive queue (entries only release on a dispatch AFTER their
    transfer completes). Called by the wire layer after each
    deserialize; no-op (one thread-local read) when nothing landed."""
    pending = getattr(_LANDING, "pending", None)
    if not pending:
        return
    _LANDING.pending = []
    jax = _jax()
    if jax is None:  # pragma: no cover - queue only fills after a land
        return
    try:
        jax.block_until_ready(pending)
    except Exception:  # noqa: BLE001 - backend torn down mid-shutdown
        return
    _drain_transfer_keepalive(jax)


def landing_zone_worthwhile() -> bool:
    """Whether a socket fetch should overlap H2D with recv via a
    :class:`DeviceLandingZone`. True on non-host-aliasing backends
    (there is a real H2D hop to hide); on the CPU backend the arena IS
    host memory, so in-flight device_put of raw frame bytes would add a
    copy instead of hiding one — gate it off unless
    ``RAY_TPU_DEVICE_LAND_ALWAYS`` forces it (tests / A-B)."""
    if not device_plane_enabled():
        return False
    try:
        from ray_tpu.config import cfg

        if cfg.device_land_always:
            return True
    except Exception:  # noqa: BLE001 - config unavailable
        pass
    jax = _jax()
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 - no devices
        return False


# ---------------------------------------------------------------------------
# seal side: the device-aware pickler
# ---------------------------------------------------------------------------


def make_device_reducer(pump_threshold: Optional[int] = None):
    """Reducer for sealable jax leaves, shaped for ``reducer_override``.

    Leaves at or above ``pump_threshold`` bytes on a non-host-aliasing
    backend read out through :class:`DeviceChunkPump` (chunked
    ``copy_to_host_async``, overlapping readout with the consumer's
    gather-copy); below it, one plain export."""
    import pickle

    from ray_tpu.config import cfg

    threshold = (
        int(cfg.device_pump_min_bytes)
        if pump_threshold is None
        else pump_threshold
    )

    def _reduce(arr):
        meta = {"d": arr.dtype.name, "s": list(arr.shape)}
        if arr.nbytes >= threshold:
            host, zero_copy = _pumped_export(arr)
        else:
            host, zero_copy = export_device_view(arr)
        _stats["device_frame_seals_total"] += 1
        if zero_copy:
            _stats["device_frame_zero_copy_total"] += 1
        # frames travel as raw bytes: extension dtypes (bfloat16,
        # float8) have no buffer-protocol format char, and meta already
        # carries dtype by name — a uint8 view is always exportable and
        # stays zero-copy (contiguity is guaranteed by the export)
        raw = host.reshape(-1).view(np.uint8)
        return _land_device_leaf, (meta, pickle.PickleBuffer(raw))

    return _reduce


def _host_aliasing(arr) -> bool:
    """True when the array's buffer lives in host-addressable memory
    (CPU backend): the plain export is zero-copy or one cheap move, and
    the pump has no D2H readout to overlap."""
    try:
        return next(iter(arr.sharding.device_set)).platform == "cpu"
    except Exception:  # noqa: BLE001 - backend without platform info
        return False


def _pumped_export(arr) -> Tuple[np.ndarray, bool]:
    """Export via the chunked D2H pump when the buffer does NOT alias
    host memory. The probe is the device platform, NOT a trial export:
    ``export_device_view`` on a non-dlpack accelerator buffer performs a
    full monolithic D2H readout, so probing with it would pay double
    device bandwidth plus a discarded host materialization on exactly
    the path the pump exists for."""
    if _host_aliasing(arr):
        return export_device_view(arr)
    pump = DeviceChunkPump(arr)
    return pump.gather(), False


class DeviceAwarePickler:
    """Mixin factory: builds a CloudPickler subclass whose
    ``reducer_override`` seals jax leaves as device frames. Constructed
    lazily (cloudpickle import stays off the module import path)."""

    _cls = None

    @classmethod
    def pickler_class(cls):
        if cls._cls is None:
            import cloudpickle

            class _P(cloudpickle.CloudPickler):
                _device_reduce: Optional[Callable] = None

                def reducer_override(self, obj):
                    red = self._device_reduce
                    if red is not None and is_sealable_device_array(obj):
                        return red(obj)
                    return super().reducer_override(obj)

            cls._cls = _P
        return cls._cls


def dumps_oob(obj: Any, protocol: int, buffer_callback) -> bytes:
    """Device-aware drop-in for ``cloudpickle.dumps(obj, protocol,
    buffer_callback=...)``: jax leaves seal as device frames when the
    plane is enabled; everything else (and the disabled path) follows
    cloudpickle exactly."""
    import io

    import cloudpickle

    if not device_plane_enabled():
        return cloudpickle.dumps(
            obj, protocol=protocol, buffer_callback=buffer_callback
        )
    f = io.BytesIO()
    p = DeviceAwarePickler.pickler_class()(
        f, protocol=protocol, buffer_callback=buffer_callback
    )
    p._device_reduce = make_device_reducer()
    p.dump(obj)
    return f.getvalue()


# ---------------------------------------------------------------------------
# chunked D2H pump (seal side, non-host-aliasing backends)
# ---------------------------------------------------------------------------


class DeviceChunkPump:
    """Chunked ``copy_to_host_async`` readout of one device array.

    Splits the flattened array into ``chunk_bytes`` windows, keeps up to
    ``depth`` async D2H copies in flight, and yields host chunks in
    order — the consumer (arena gather-copy / socket send loop) works on
    chunk *k* while chunks *k+1..k+depth* read out. The whole tensor is
    never materialized host-side ahead of its consumer; records one
    ``d2h_overlap_ms`` span per drained pump."""

    def __init__(
        self,
        arr,
        chunk_bytes: Optional[int] = None,
        depth: Optional[int] = None,
    ):
        from ray_tpu.config import cfg

        self.arr = arr
        self.chunk_bytes = max(
            1 << 20,
            int(cfg.device_pump_chunk_bytes)
            if chunk_bytes is None
            else chunk_bytes,
        )
        self.depth = max(
            1, int(cfg.device_pump_depth) if depth is None else depth
        )

    def chunks(self) -> Iterator[Tuple[int, np.ndarray]]:
        """Yield ``(byte_offset, host_chunk)`` in order with D2H
        lookahead."""
        arr = self.arr
        itemsize = arr.dtype.itemsize
        per_chunk = max(1, self.chunk_bytes // itemsize)
        flat = arr.reshape(-1)
        n = flat.shape[0]
        t0 = time.time()
        tp0 = time.perf_counter()
        pending: List[Tuple[int, Any]] = []
        issued = 0
        while issued < n or pending:
            while issued < n and len(pending) < self.depth:
                part = flat[issued : issued + per_chunk]
                try:
                    part.copy_to_host_async()
                except Exception:  # noqa: BLE001 - backend without async
                    pass
                pending.append((issued, part))
                issued += min(per_chunk, n - issued)
            off, part = pending.pop(0)
            _stats["device_pump_chunks_total"] += 1
            yield off * itemsize, np.asarray(part)
        try:
            from ray_tpu.util.tracing import SPANS

            SPANS.record(
                "d2h_overlap_ms",
                "device_plane",
                t0,
                time.perf_counter() - tp0,
                bytes=int(arr.nbytes),
                chunks=int(-(-n // per_chunk)),
            )
        except Exception:  # noqa: BLE001 - observability only
            pass

    def gather(self) -> np.ndarray:
        """Drain the pump into one contiguous host ndarray (callers that
        need the whole buffer; streamed consumers iterate chunks())."""
        out = np.empty(self.arr.shape, dtype=self.arr.dtype)
        # uint8 VIEWS, not the buffer protocol: ml_dtypes extension
        # types (bfloat16, float8) have no buffer-protocol format char,
        # so memoryview(...).cast("B") raises on exactly the dtypes
        # real weights and KV pages use
        dst = out.reshape(-1).view(np.uint8)
        for off, chunk in self.chunks():
            src = np.ascontiguousarray(chunk).reshape(-1).view(np.uint8)
            dst[off : off + src.nbytes] = src
        return out


# ---------------------------------------------------------------------------
# landing zone (receive side: H2D overlapped with recv)
# ---------------------------------------------------------------------------


class DeviceLandingZone:
    """Overlaps H2D with an in-flight striped socket receive.

    Wraps the staged host destination (an unsealed arena entry or a
    bytearray view). ``note_stripe(off, n)`` is called from the fetch
    loop as each disjoint stripe lands; whenever a full
    ``chunk_bytes`` window of the CONTIGUOUS PREFIX has landed, the
    zone issues an async ``device_put`` of that window so the H2D hop
    rides under the remaining recv. ``finish()`` blocks until every
    issued chunk is device-resident and records the ``h2d_overlap_ms``
    span; ``abort()`` drops partial device buffers (their backing host
    pages are freed separately via ``abort_put``).

    The prefetched device chunks WARM the transfer (and are the whole
    result for raw single-tensor pulls, ``chunks()``); pickled objects
    still deserialize from the host staging view — their leaves'
    ``device_put`` then reads pages that are hot."""

    def __init__(self, dest, chunk_bytes: Optional[int] = None):
        from ray_tpu.config import cfg

        self.dest = dest
        self.total = dest.nbytes
        self.chunk_bytes = max(
            1 << 20,
            int(cfg.device_land_chunk_bytes)
            if chunk_bytes is None
            else chunk_bytes,
        )
        self._lock = threading.Lock()
        self._landed: List[Tuple[int, int]] = []  # merged [off, end) spans
        self._shipped = 0  # contiguous prefix bytes already device_put
        self._chunks: List[Any] = []  # device chunks, in prefix order
        self._aborted = False
        self._t0 = time.time()
        self._tp0 = time.perf_counter()
        self._h2d_s = 0.0

    # -- stripe accounting ---------------------------------------------
    def note_stripe(self, off: int, n: int) -> None:
        if n <= 0:
            return
        jax = _jax()
        with self._lock:
            if self._aborted:
                return
            self._merge(off, off + n)
            prefix = self._prefix()
            while (
                jax is not None
                and prefix - self._shipped >= self.chunk_bytes
            ) or (prefix >= self.total and self._shipped < self.total):
                a = self._shipped
                b = min(a + self.chunk_bytes, prefix, self.total)
                if b <= a:
                    break
                t0 = time.perf_counter()
                if jax is not None:
                    host = np.frombuffer(self.dest[a:b], dtype=np.uint8)
                    # async: device_put returns immediately, the copy
                    # overlaps with the next stripes' recv
                    self._chunks.append(jax.device_put(host))
                    _stats["device_land_chunks_total"] += 1
                self._h2d_s += time.perf_counter() - t0
                self._shipped = b

    def _merge(self, a: int, b: int) -> None:
        spans = self._landed
        spans.append((a, b))
        spans.sort()
        merged = [spans[0]]
        for s, e in spans[1:]:
            ls, le = merged[-1]
            if s <= le:
                merged[-1] = (ls, max(le, e))
            else:
                merged.append((s, e))
        self._landed = merged

    def _prefix(self) -> int:
        if not self._landed or self._landed[0][0] != 0:
            return 0
        return self._landed[0][1]

    # -- completion ----------------------------------------------------
    def finish(self) -> List[Any]:
        """Block until every issued chunk is device-resident; returns
        the ordered device chunks (uint8, covering the whole object for
        a fully-landed transfer)."""
        with self._lock:
            # a transfer smaller than one chunk (or whose tail stripe
            # was the last to land) ships its remainder here
            jax = _jax()
            if (
                jax is not None
                and not self._aborted
                and self._shipped < self.total
                and self._prefix() >= self.total
            ):
                t0 = time.perf_counter()
                host = np.frombuffer(
                    self.dest[self._shipped : self.total], dtype=np.uint8
                )
                self._chunks.append(jax.device_put(host))
                _stats["device_land_chunks_total"] += 1
                self._h2d_s += time.perf_counter() - t0
                self._shipped = self.total
            chunks = list(self._chunks)
        jax = _jax()
        if jax is not None and chunks:
            t0 = time.perf_counter()
            try:
                jax.block_until_ready(chunks)
            except Exception:  # noqa: BLE001 - backend torn down
                pass
            # the zone's device_put sources are np.frombuffer views over
            # the staged arena entry: drain the keepalive NOW so the
            # pages unpin with the fetch, not at some future dispatch —
            # same zombie-page contract as flush_landing_keepalive
            _drain_transfer_keepalive(jax)
            self._h2d_s += time.perf_counter() - t0
        try:
            from ray_tpu.util.tracing import SPANS

            SPANS.record(
                "h2d_overlap_ms",
                "device_plane",
                self._t0,
                time.perf_counter() - self._tp0,
                bytes=int(self.total),
                chunks=len(chunks),
                h2d_ms=round(self._h2d_s * 1e3, 3),
            )
        except Exception:  # noqa: BLE001 - observability only
            pass
        return chunks

    def abort(self) -> None:
        """Drop partial device buffers. The staged HOST pages are the
        caller's to free (``store.abort_put`` — the zone never owns
        them), so an aborted device landing leaks neither side."""
        with self._lock:
            self._aborted = True
            chunks, self._chunks = self._chunks, []
        for c in chunks:
            try:
                c.delete()
            except Exception:  # noqa: BLE001 - already deleted/donated
                pass
        if chunks:
            jax = _jax()
            if jax is not None:
                # issued transfers may still hold keepalive refs on the
                # staged pages the caller is about to abort_put
                _drain_transfer_keepalive(jax)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "total": self.total,
                "prefix": self._prefix(),
                "shipped": self._shipped,
                "chunks": len(self._chunks),
                "aborted": self._aborted,
            }


def assemble_device_tensor(
    chunks: Sequence[Any], dtype_name: str, shape: Sequence[int]
):
    """Reassemble a device tensor from a landing zone's ordered uint8
    chunks — concatenate + bitcast + reshape run ON DEVICE, so the raw
    single-tensor receive path (rdt) never builds a second host copy."""
    jax = _jax()
    if jax is None:
        raise RuntimeError(
            "assemble_device_tensor requires jax: landing-zone chunks "
            "are device-resident and cannot reassemble host-side"
        )
    import jax.numpy as jnp
    flat = chunks[0] if len(chunks) == 1 else jnp.concatenate(list(chunks))
    dt = resolve_dtype(dtype_name)
    return jax.lax.bitcast_convert_type(
        flat.reshape(-1, dt.itemsize), dt
    ).reshape(tuple(shape)) if dt.itemsize > 1 else flat.view(dt).reshape(
        tuple(shape)
    )


def debug_block() -> dict:
    """DebugState ``object_plane.device`` block (agent/worker surfaces)."""
    out = {"enabled": device_plane_enabled()}
    out.update(device_stats())
    return out
