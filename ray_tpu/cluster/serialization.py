"""Zero-copy wire format: pickle protocol 5 with out-of-band buffers.

The control plane's previous wire format was a monolithic
``cloudpickle.dumps``: every numpy block rode inside the pickle byte
string and was re-copied at each hop (serialize → gRPC frame →
deserialize). This module frames the pickle stream and its out-of-band
buffers (PEP 574) into one self-describing blob:

    MAGIC | u16 version | u16 nbufs | u64 pkl_len | nbufs x u64 buf_len
          | pickle bytes | raw buffers...

On the way OUT, large contiguous buffers (numpy arrays, anything whose
``__reduce_ex__`` emits a ``PickleBuffer`` at protocol 5) skip the pickle
stream entirely — one gather-copy into the frame instead of a pickle
memo pass. On the way IN, ``loads`` hands pickle zero-copy memoryview
slices of the incoming frame, so a numpy array reconstructs as a
READ-ONLY VIEW over the network buffer / shm arena page it arrived in —
no per-hop copy (the plasma + pickle5 contract the reference uses,
serialization.py out-of-band path).

``loads`` transparently falls back to ``cloudpickle``-compatible plain
pickles (no magic prefix), so mixed callers and on-disk spill files from
either format keep working.
"""
from __future__ import annotations

import io
import pickle
import struct
from typing import Any, List, Sequence, Tuple

import cloudpickle

MAGIC = b"RTP5"
_HDR = struct.Struct("<HHQ")  # version, nbufs, pickle_len
_LEN = struct.Struct("<Q")
_VERSION = 1

# buffers smaller than this stay in-band: framing overhead + a second
# syscall-sized copy beat the win for tiny arrays
OOB_MIN_BUFFER = 4096


def dumps_parts(obj: Any) -> Tuple[List[Any], int]:
    """Serialize to ``(parts, total_len)`` without concatenating.

    ``parts[0]`` is the frame header + pickle bytes; the rest are the
    out-of-band buffers (memoryviews over the ORIGINAL objects — no
    copy has happened yet). Callers that can write scatter/gather (the
    shm arena put path) stream the parts straight into place; everyone
    else joins via :func:`dumps`.
    """
    buffers: List[memoryview] = []

    def _cb(buf: pickle.PickleBuffer):
        try:
            raw = buf.raw()
        except BufferError:
            return True  # non-contiguous: pickle copies it in-band
        if raw.nbytes < OOB_MIN_BUFFER:
            return True
        buffers.append(raw)
        return False  # carried out-of-band

    pkl = cloudpickle.dumps(obj, protocol=5, buffer_callback=_cb)
    if not buffers:
        return [pkl], len(pkl)
    head = bytearray(MAGIC)
    head += _HDR.pack(_VERSION, len(buffers), len(pkl))
    for b in buffers:
        head += _LEN.pack(b.nbytes)
    head += pkl
    total = len(head) + sum(b.nbytes for b in buffers)
    return [bytes(head), *buffers], total


def dumps(obj: Any) -> bytes:
    """One-blob form of :func:`dumps_parts` (bytes for the RPC layer)."""
    parts, _ = dumps_parts(obj)
    if len(parts) == 1:
        return parts[0]
    return b"".join(
        p if isinstance(p, bytes) else bytes(p) for p in parts
    )


def loads(data) -> Any:
    """Deserialize bytes/memoryview produced by :func:`dumps` (or any
    plain pickle — no-magic inputs fall through to ``pickle.loads``).

    Out-of-band buffers resolve to memoryview SLICES of ``data``: numpy
    arrays come back as zero-copy read-only views for the lifetime of
    the backing buffer (which they keep alive)."""
    mv = data if isinstance(data, memoryview) else memoryview(data)
    if mv.nbytes < 4 or bytes(mv[:4]) != MAGIC:
        return pickle.loads(mv)
    off = 4
    version, nbufs, pkl_len = _HDR.unpack_from(mv, off)
    off += _HDR.size
    if version != _VERSION:
        raise ValueError(f"unknown wire-format version {version}")
    lens = [
        _LEN.unpack_from(mv, off + i * _LEN.size)[0] for i in range(nbufs)
    ]
    off += nbufs * _LEN.size
    pkl = mv[off : off + pkl_len]
    off += pkl_len
    bufs = []
    for n in lens:
        bufs.append(mv[off : off + n])
        off += n
    return pickle.loads(pkl, buffers=bufs)


def frames_total(parts: Sequence[Any]) -> int:
    return sum(
        p.nbytes if isinstance(p, memoryview) else len(p) for p in parts
    )


def join_parts(parts: Sequence[Any]) -> bytes:
    if len(parts) == 1 and isinstance(parts[0], bytes):
        return parts[0]
    buf = io.BytesIO()
    for p in parts:
        buf.write(p)
    return buf.getvalue()
