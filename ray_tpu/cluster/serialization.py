"""Zero-copy wire format: pickle protocol 5 with out-of-band buffers.

The control plane's previous wire format was a monolithic
``cloudpickle.dumps``: every numpy block rode inside the pickle byte
string and was re-copied at each hop (serialize → gRPC frame →
deserialize). This module frames the pickle stream and its out-of-band
buffers (PEP 574) into one self-describing blob:

    MAGIC | u16 version | u16 nbufs | u64 pkl_len | nbufs x u64 buf_len
          | pickle bytes | raw buffers...

On the way OUT, large contiguous buffers (numpy arrays, anything whose
``__reduce_ex__`` emits a ``PickleBuffer`` at protocol 5) skip the pickle
stream entirely — one gather-copy into the frame instead of a pickle
memo pass. On the way IN, ``loads`` hands pickle zero-copy memoryview
slices of the incoming frame, so a numpy array reconstructs as a
READ-ONLY VIEW over the network buffer / shm arena page it arrived in —
no per-hop copy (the plasma + pickle5 contract the reference uses,
serialization.py out-of-band path).

``loads`` transparently falls back to ``cloudpickle``-compatible plain
pickles (no magic prefix), so mixed callers and on-disk spill files from
either format keep working.

**Framing hot path**: the frame build (header pack + buffer-length table
+ gather join) and parse (header validation + offset-table scan) run in
C when ``native/wire.cc`` compiles (see :data:`NATIVE_WIRE`) — one FFI
call instead of O(nbufs) interpreter ops per frame. The pure-Python
implementation below is the import-failure fallback and stays the
reference semantics; ``RAY_TPU_NATIVE_WIRE=0`` is the kill switch.
Pickling itself always stays in Python (cloudpickle owns object graphs).
"""
from __future__ import annotations

import ctypes
import os
import pickle
import struct
from typing import Any, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu.cluster import device_plane

MAGIC = b"RTP5"
_HDR = struct.Struct("<HHQ")  # version, nbufs, pickle_len
_LEN = struct.Struct("<Q")
_VERSION = 1
_FIXED = 4 + _HDR.size  # magic + fixed header

# buffers smaller than this stay in-band: framing overhead + a second
# syscall-sized copy beat the win for tiny arrays
OOB_MIN_BUFFER = 4096

# hot-path counters (plain-int increments — a locked Counter.inc per
# frame would reintroduce the per-item Python cost this module exists to
# remove). `d[k] += 1` is NOT strictly atomic (a thread switch between
# the load and store can drop an increment), which is an accepted trade:
# these are rate indicators, and the flat-vs-nonzero fallback proof is
# race-safe — racing first increments may under-count but can never
# leave a used path at zero. publish_wire_metrics() syncs the values
# into the registry for scrapes/DebugState.
_stats = {
    "native_wire_dumps_total": 0,
    "native_wire_loads_total": 0,
    "native_wire_dumps_fallback_total": 0,
    "native_wire_loads_fallback_total": 0,
}


def wire_stats() -> dict:
    return dict(_stats)


def publish_wire_metrics() -> dict:
    """Sync the hot-path counters into the metrics registry (called from
    observability surfaces, never the wire path itself)."""
    from ray_tpu.util.metrics import sync_counter

    for name, v in _stats.items():
        sync_counter(
            name, v, "RTP5 framing calls (native C path vs Python fallback)."
        )
    return wire_stats()


# ---------------------------------------------------------------------------
# native framing library (wire.cc), selected once at import
# ---------------------------------------------------------------------------


def _load_native_wire():
    from ray_tpu.native.build import build_native

    lib = ctypes.CDLL(build_native("wire"))
    lib.rtpu_wire_frame_size.restype = ctypes.c_uint64
    lib.rtpu_wire_frame_size.argtypes = [
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint32,
    ]
    lib.rtpu_wire_join.restype = ctypes.c_int64
    lib.rtpu_wire_join.argtypes = [
        ctypes.c_char_p,  # pickle bytes
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_void_p),  # buffer pointers
        ctypes.POINTER(ctypes.c_uint64),  # buffer lengths
        ctypes.c_uint32,
        ctypes.c_void_p,  # dst
        ctypes.c_uint64,
    ]
    lib.rtpu_wire_parse.restype = ctypes.c_int64
    lib.rtpu_wire_parse.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint32,
    ]
    return lib


def _native_wire_enabled() -> bool:
    try:
        from ray_tpu.config import cfg

        return bool(cfg.native_wire)  # env: RAY_TPU_NATIVE_WIRE
    except Exception:  # noqa: BLE001 - config unavailable (bootstrap)
        return os.environ.get("RAY_TPU_NATIVE_WIRE", "1").lower() not in (
            "0",
            "false",
            "no",
        )


_NATIVE = None
if _native_wire_enabled():
    try:
        _NATIVE = _load_native_wire()
    except Exception:  # noqa: BLE001 - toolchain missing: Python fallback
        _NATIVE = None
if _NATIVE is not None:
    try:
        # dark-plane counters: hand the C library this process's
        # shm-resident slot page — frames/bytes count where they move,
        # read out on the observability tick (native/counters.py)
        from ray_tpu.native import counters as _dark_counters

        _dark_counters.register_with_wire(_NATIVE)
    except Exception:  # noqa: BLE001 - counting is optional
        pass

#: True when the C framing path is active for this process.
NATIVE_WIRE = _NATIVE is not None

# CPython-only single-copy output: allocate an UNINITIALIZED bytes object
# and let the C join write straight into it (safe: the object is mutated
# before any other reference can observe it — the idiom bytes.join and
# pickle use internally). ctypes is already a hard dependency of every
# native component here.
_PyBytes_New = ctypes.pythonapi.PyBytes_FromStringAndSize
_PyBytes_New.restype = ctypes.py_object
_PyBytes_New.argtypes = [ctypes.c_char_p, ctypes.c_ssize_t]
_PyBytes_AsString = ctypes.pythonapi.PyBytes_AsString
_PyBytes_AsString.restype = ctypes.c_void_p
_PyBytes_AsString.argtypes = [ctypes.py_object]


def _buf_addr(mv: memoryview) -> Tuple[int, Any]:
    """(address, keepalive) for a contiguous (possibly read-only)
    buffer. ctypes ``from_buffer`` refuses read-only views; numpy's
    zero-copy frombuffer hands back the data pointer either way."""
    import numpy as np

    if mv.nbytes == 0:
        return 0, None
    arr = np.frombuffer(mv, dtype=np.uint8)
    return int(arr.ctypes.data), arr


def _pickle_oob(obj: Any) -> Tuple[bytes, List[memoryview]]:
    """The shared pickling front half: protocol-5 dump collecting
    out-of-band buffers >= OOB_MIN_BUFFER."""
    buffers: List[memoryview] = []

    def _cb(buf: pickle.PickleBuffer):
        try:
            raw = buf.raw()
        except BufferError:
            return True  # non-contiguous: pickle copies it in-band
        if raw.nbytes < OOB_MIN_BUFFER:
            return True
        if len(buffers) >= 0xFFFF:
            # the frame header's nbufs field is u16: anything past 65535
            # buffers rides in-band (slower, never unrepresentable)
            return True
        buffers.append(raw)
        return False  # carried out-of-band

    # device-aware front half: sealable jax.Array leaves reduce to
    # device frames (PickleBuffer exports of the device buffer) instead
    # of cloudpickle's full host-copy reducer; the PickleBuffers flow
    # through _cb like any other out-of-band buffer, so device frames
    # ride RTP5 unchanged and every transport/degradation rung below
    # this line (arena, socket, chunked RPC, spill) works untouched.
    pkl = device_plane.dumps_oob(obj, protocol=5, buffer_callback=_cb)
    return pkl, buffers


def _build_head(pkl_len: int, buffers: Sequence[memoryview]) -> bytearray:
    """Frame head (magic + header + length table) — one preallocated
    bytearray, one pack per section (no per-buffer += growth)."""
    n = len(buffers)
    head = bytearray(_FIXED + n * 8)
    head[:4] = MAGIC
    _HDR.pack_into(head, 4, _VERSION, n, pkl_len)
    struct.pack_into(f"<{n}Q", head, _FIXED, *(b.nbytes for b in buffers))
    return head


def dumps_parts(obj: Any) -> Tuple[List[Any], int]:
    """Serialize to ``(parts, total_len)`` without concatenating.

    ``parts[0]`` is the frame header + pickle bytes; the rest are the
    out-of-band buffers (memoryviews over the ORIGINAL objects — no
    copy has happened yet). Callers that can write scatter/gather (the
    shm arena put path) stream the parts straight into place; everyone
    else joins via :func:`dumps`.
    """
    pkl, buffers = _pickle_oob(obj)
    if not buffers:
        return [pkl], len(pkl)
    head = _build_head(len(pkl), buffers)
    head += pkl
    total = len(head) + sum(b.nbytes for b in buffers)
    return [bytes(head), *buffers], total


def dumps(obj: Any) -> bytes:
    """One-blob form of :func:`dumps_parts` (bytes for the RPC layer).

    Single-copy: the frame is gather-built into ONE preallocated buffer
    (C ``rtpu_wire_join`` when available; memoryview slice-writes
    otherwise) — no intermediate ``bytes(part)`` copies, no join pass."""
    pkl, buffers = _pickle_oob(obj)
    if not buffers:
        return pkl
    n = len(buffers)  # <= 0xFFFF by the _pickle_oob callback cap
    if _NATIVE is not None:
        lens = (ctypes.c_uint64 * n)(*(b.nbytes for b in buffers))
        ptrs = (ctypes.c_void_p * n)()
        keep = []
        for i, b in enumerate(buffers):
            addr, ka = _buf_addr(b)
            ptrs[i] = addr
            keep.append(ka)
        total = _NATIVE.rtpu_wire_frame_size(len(pkl), lens, n)
        if total:
            out = _PyBytes_New(None, total)
            wrote = _NATIVE.rtpu_wire_join(
                pkl, len(pkl), ptrs, lens, n, _PyBytes_AsString(out), total
            )
            if wrote == total:
                _stats["native_wire_dumps_total"] += 1
                return out
    # counted ONLY when the frame was actually built in Python — the
    # bench's "fallback counters flat" proof must see every miss
    _stats["native_wire_dumps_fallback_total"] += 1
    # bytes.join accepts any buffer — ONE gather copy of head + pickle +
    # buffers, no per-part bytes() conversions (the old double copy)
    head = _build_head(len(pkl), buffers)
    head += pkl
    return b"".join([head, *buffers])


def _parse_frame(mv: memoryview) -> Tuple[memoryview, List[memoryview]]:
    """(pickle_view, buffer_views) for a magic-prefixed frame; raises
    ``ValueError`` on truncation/corruption. Native parse validates the
    whole offset table in one call; the Python path mirrors it."""
    if _NATIVE is not None:
        _stats["native_wire_loads_total"] += 1
        # nbufs peek sizes the offset table; the native parse re-checks
        # every bound (a lying header fails there, not here)
        if mv.nbytes < _FIXED:
            raise ValueError("truncated wire frame (no header)")
        nbufs = _HDR.unpack_from(mv, 4)[1]
        out = (ctypes.c_uint64 * (2 + 2 * nbufs))()
        addr, keep = _buf_addr(mv)
        rc = _NATIVE.rtpu_wire_parse(addr, mv.nbytes, out, nbufs)
        del keep
        if rc == -3:
            raise ValueError(
                f"unknown wire-format version {_HDR.unpack_from(mv, 4)[0]}"
            )
        if rc < 0:
            raise ValueError("truncated or corrupt wire frame")
        pkl = mv[out[0] : out[0] + out[1]]
        bufs = [
            mv[out[2 + 2 * i] : out[2 + 2 * i] + out[3 + 2 * i]]
            for i in range(rc)
        ]
        return pkl, bufs
    _stats["native_wire_loads_fallback_total"] += 1
    if mv.nbytes < _FIXED:
        raise ValueError("truncated wire frame (no header)")
    version, nbufs, pkl_len = _HDR.unpack_from(mv, 4)
    if version != _VERSION:
        raise ValueError(f"unknown wire-format version {version}")
    off = _FIXED + nbufs * 8
    if off > mv.nbytes or pkl_len > mv.nbytes - off:
        raise ValueError("truncated or corrupt wire frame")
    lens = struct.unpack_from(f"<{nbufs}Q", mv, _FIXED)
    pkl = mv[off : off + pkl_len]
    off += pkl_len
    bufs = []
    for blen in lens:
        if blen > mv.nbytes - off:
            raise ValueError("truncated or corrupt wire frame")
        bufs.append(mv[off : off + blen])
        off += blen
    return pkl, bufs


def loads(data) -> Any:
    """Deserialize bytes/memoryview produced by :func:`dumps` (or any
    plain pickle — no-magic inputs fall through to ``pickle.loads``).

    Out-of-band buffers resolve to memoryview SLICES of ``data``: numpy
    arrays come back as zero-copy read-only views for the lifetime of
    the backing buffer (which they keep alive)."""
    mv = data if isinstance(data, memoryview) else memoryview(data)
    if mv.nbytes < 4 or bytes(mv[:4]) != MAGIC:
        return pickle.loads(mv)
    pkl, bufs = _parse_frame(mv)
    try:
        return pickle.loads(pkl, buffers=bufs)
    finally:
        # device frames landed during this deserialize leave their
        # view-backed source in jax's transfer keepalive — evict it so
        # the arena pin dies with the views, not at the next dispatch
        device_plane.flush_landing_keepalive()


def frames_total(parts: Sequence[Any]) -> int:
    return sum(
        p.nbytes if isinstance(p, memoryview) else len(p) for p in parts
    )


def join_parts(parts: Sequence[Any]) -> bytes:
    """Join scatter parts into one blob. ``bytes.join`` gather-copies
    every part (bytes or memoryview) exactly once into a preallocated
    result — the old ``io.BytesIO`` round trip grew and re-copied."""
    if len(parts) == 1 and isinstance(parts[0], bytes):
        return parts[0]
    return b"".join(parts)
