"""Per-requirements pip runtime environments, agent-side.

Capability analog of the reference's pip/uv runtime-env builders
(/root/reference/python/ray/_private/runtime_env/pip.py, uv.py: cache
keyed by a hash of the resolved config, concurrent builds deduplicated,
idle environments garbage-collected).

Redesigned for this runtime: instead of full virtualenvs (venv +
ensurepip cost per env), an environment is a ``pip install --target``
directory keyed by the hash of its normalized requirements + install
args + interpreter version. A worker serving the env runs with the
directory prepended to ``sys.path``, shadowing base site-packages — so
two workers on one node can hold conflicting versions of the same
package concurrently, which is the isolation property the builders
exist for. Builds are serialized per key with a file lock; the winner
writes a completion marker, losers wait on it.

No-network images: callers pass explicit install args (e.g.
``--no-index --find-links /wheels``); nothing here reaches for an index
by itself beyond what pip is told.
"""
from __future__ import annotations

import fcntl
import hashlib
import os
import shutil
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple


def normalize_pip(pip) -> Tuple[List[str], List[str]]:
    """Accepts the reference's shapes: a list of requirement strings, or
    {"packages": [...], "pip_install_args"/"install_args": [...]}."""
    if pip is None:
        return [], []
    if isinstance(pip, (list, tuple)):
        return sorted(str(p) for p in pip), []
    if isinstance(pip, dict):
        pkgs = sorted(str(p) for p in pip.get("packages", ()))
        args = list(
            pip.get("pip_install_args") or pip.get("install_args") or ()
        )
        return pkgs, args
    raise TypeError(f"runtime_env['pip'] must be list or dict, got {pip!r}")


class PipEnvManager:
    """Hash-keyed --target environments with refcounts and LRU GC."""

    BUILD_TIMEOUT_S = 600.0

    def __init__(self, base_dir: str, max_cached: int = 8):
        self.base_dir = base_dir
        self.max_cached = max_cached
        os.makedirs(base_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._refs: Dict[str, int] = {}

    # ------------------------------------------------------------------
    def key_of(self, pip) -> str:
        pkgs, args = normalize_pip(pip)
        blob = "\n".join(
            pkgs + ["--"] + args + [sys.version.split()[0]]
        ).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def env_dir(self, key: str) -> str:
        return os.path.join(self.base_dir, key)

    def ensure(self, pip) -> Tuple[str, str]:
        """Return (key, env_dir), building the environment if it doesn't
        exist yet. Concurrent callers for one key serialize on a file
        lock; only the winner runs pip."""
        pkgs, args = normalize_pip(pip)
        key = self.key_of(pip)
        env_dir = self.env_dir(key)
        marker = env_dir + ".built"
        with self._lock:  # serialized vs gc(): marker+dir vanish atomically
            if os.path.exists(marker):
                return key, env_dir
        lock_path = env_dir + ".lock"
        with open(lock_path, "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                if os.path.exists(marker):  # built while we waited
                    return key, env_dir
                tmp = env_dir + ".tmp"
                shutil.rmtree(tmp, ignore_errors=True)
                cmd = [
                    sys.executable,
                    "-m",
                    "pip",
                    "install",
                    "--target",
                    tmp,
                    "--disable-pip-version-check",
                    "--no-input",
                    *args,
                    *pkgs,
                ]
                proc = subprocess.run(
                    cmd,
                    capture_output=True,
                    text=True,
                    timeout=self.BUILD_TIMEOUT_S,
                    env={**os.environ, "PIP_NO_COLOR": "1"},
                )
                if proc.returncode != 0:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise RuntimeError(
                        f"pip env build failed (key {key}): "
                        + (proc.stderr or proc.stdout)[-1500:]
                    )
                shutil.rmtree(env_dir, ignore_errors=True)
                os.replace(tmp, env_dir)
                with open(marker, "w") as mf:
                    mf.write(" ".join(pkgs))
                return key, env_dir
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

    # ------------------------------------------------------------------
    def acquire(self, key: str) -> None:
        with self._lock:
            self._refs[key] = self._refs.get(key, 0) + 1

    def release(self, key: str) -> None:
        with self._lock:
            c = self._refs.get(key, 0) - 1
            if c <= 0:
                self._refs.pop(key, None)
            else:
                self._refs[key] = c

    def gc(self) -> int:
        """Remove unreferenced environments beyond max_cached, oldest
        first (the reference GCs per-env on last-actor-exit; a small LRU
        cache keeps warm envs for repeat jobs). Returns removed count.

        The lock covers only the cheap part — liveness read, marker
        unlink, and an atomic rename of each doomed dir to a .tmp name —
        so an acquire() racing the sweep either lands before the read
        (env survives) or sees the env already gone and rebuilds. The
        slow recursive deletes run after the lock is released (rmtree of
        a large env must not stall pip dispatch node-wide)."""
        doomed: List[str] = []
        with self._lock:
            live = set(self._refs)
            envs = []
            try:
                for name in os.listdir(self.base_dir):
                    p = os.path.join(self.base_dir, name)
                    if not os.path.isdir(p):
                        continue
                    if name.endswith(".gc.tmp"):
                        # grave from a sweep interrupted by process death:
                        # always finish the burial
                        doomed.append(p)
                    elif not name.endswith(".tmp"):
                        envs.append((os.path.getmtime(p), name))
            except OSError:
                return 0
            envs.sort()
            removed = 0
            excess = len(envs) - self.max_cached
            for _, name in envs:
                if excess <= removed or name in live:
                    continue
                for suffix in (".built", ".lock"):
                    try:
                        os.unlink(os.path.join(self.base_dir, name + suffix))
                    except OSError:
                        pass
                grave = os.path.join(
                    self.base_dir, f"{name}.{os.getpid()}.gc.tmp"
                )
                try:
                    os.rename(os.path.join(self.base_dir, name), grave)
                except OSError:
                    continue
                doomed.append(grave)
                removed += 1
        for grave in doomed:
            shutil.rmtree(grave, ignore_errors=True)
        return removed
