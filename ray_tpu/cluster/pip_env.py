"""Per-requirements pip/uv/conda runtime environments, agent-side.

Capability analog of the reference's runtime-env builders
(/root/reference/python/ray/_private/runtime_env/pip.py, uv.py,
conda.py: cache keyed by a hash of the resolved config, concurrent
builds deduplicated, idle environments garbage-collected).

Redesigned for this runtime: instead of full virtualenvs (venv +
ensurepip cost per env), a ``pip`` or ``uv`` environment is an
``install --target`` directory keyed by the hash of its normalized
requirements + install args + interpreter version. A worker serving the
env runs with the directory prepended to ``sys.path``, shadowing base
site-packages — so two workers on one node can hold conflicting
versions of the same package concurrently, which is the isolation
property the builders exist for. A ``conda`` environment is a full
env directory (``conda create -p``) whose OWN interpreter runs the
worker — the env must therefore provide python and have ray_tpu
importable (reference conda.py injects ray the same way). All kinds
share one key/lock/refcount/GC machinery: builds are serialized per key
with a file lock; the winner writes a completion marker, losers wait on
it.

No-network images: callers pass explicit install args (e.g.
``--no-index --find-links /wheels``); nothing here reaches for an index
by itself beyond what the tool is told. The conda binary resolves from
``RAY_TPU_CONDA_BINARY`` or PATH (conda/mamba/micromamba) and its
absence is a loud build error, not a silent fallback.
"""
from __future__ import annotations

import fcntl
import hashlib
import os
import shutil
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple


ENV_KINDS = ("pip", "uv", "conda")


def has_env(runtime_env) -> bool:
    """True when a runtime_env needs an isolated-env-bound worker."""
    return bool(runtime_env) and any(
        runtime_env.get(k) is not None for k in ENV_KINDS
    )


def env_slice(runtime_env) -> Optional[Dict[str, object]]:
    """The isolated-env portion of a runtime_env: {"pip": ...},
    {"uv": ...}, or {"conda": ...} (at most one), else None."""
    if not runtime_env:
        return None
    present = [k for k in ENV_KINDS if runtime_env.get(k) is not None]
    if not present:
        return None
    if len(present) > 1:
        raise ValueError(
            f"runtime_env may specify at most one of {ENV_KINDS}, "
            f"got {present}"
        )
    k = present[0]
    return {k: runtime_env[k]}


def normalize_pip(pip) -> Tuple[List[str], List[str]]:
    """Accepts the reference's shapes: a list of requirement strings, or
    {"packages": [...], "pip_install_args"/"install_args": [...]}."""
    if pip is None:
        return [], []
    if isinstance(pip, (list, tuple)):
        return sorted(str(p) for p in pip), []
    if isinstance(pip, dict):
        pkgs = sorted(str(p) for p in pip.get("packages", ()))
        args = list(
            pip.get("pip_install_args")
            or pip.get("uv_pip_install_args")
            or pip.get("conda_create_args")
            or pip.get("install_args")
            or ()
        )
        return pkgs, args
    raise TypeError(f"runtime_env env spec must be list or dict, got {pip!r}")


def normalize_conda(spec) -> Tuple[List[str], List[str]]:
    """Accepts a list of package specs or a dict with "packages" OR the
    reference environment-yaml shape's "dependencies" list (conda.py).
    Nested dependency specs (e.g. {"pip": [...]} inside dependencies)
    are rejected loudly — silently dropping them would cache an env
    missing what the user asked for."""
    if spec is None:
        return [], []
    if isinstance(spec, (list, tuple)):
        deps: List[object] = list(spec)
        args: List[str] = []
    elif isinstance(spec, dict):
        deps = list(spec.get("packages") or spec.get("dependencies") or ())
        args = list(
            spec.get("conda_create_args") or spec.get("install_args") or ()
        )
    else:
        raise TypeError(
            f"runtime_env['conda'] must be list or dict, got {spec!r}"
        )
    bad = [d for d in deps if not isinstance(d, str)]
    if bad:
        raise TypeError(
            "nested conda dependency specs are not supported "
            f"(got {bad!r}); list plain 'name=version' strings"
        )
    return sorted(str(d) for d in deps), args


def _normalize_any(env) -> Tuple[str, List[str], List[str]]:
    """(kind, packages, args) from either a {"pip"/"uv"/"conda": spec}
    slice or a bare pip spec (legacy callers)."""
    if isinstance(env, dict) and len(env) == 1 and next(iter(env)) in ENV_KINDS:
        kind = next(iter(env))
        if kind == "conda":
            pkgs, args = normalize_conda(env[kind])
        else:
            pkgs, args = normalize_pip(env[kind])
        return kind, pkgs, args
    pkgs, args = normalize_pip(env)
    return "pip", pkgs, args


def conda_binary() -> Optional[str]:
    """The conda-family binary to build envs with (injection point:
    RAY_TPU_CONDA_BINARY overrides PATH discovery — also how tests stub
    it on images without conda)."""
    override = os.environ.get("RAY_TPU_CONDA_BINARY")
    if override:
        return override
    for name in ("conda", "mamba", "micromamba"):
        path = shutil.which(name)
        if path:
            return path
    return None


class PipEnvManager:
    """Hash-keyed --target environments with refcounts and LRU GC."""

    BUILD_TIMEOUT_S = 600.0

    def __init__(self, base_dir: str, max_cached: int = 8):
        self.base_dir = base_dir
        self.max_cached = max_cached
        os.makedirs(base_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._refs: Dict[str, int] = {}

    # ------------------------------------------------------------------
    def key_of(self, env) -> str:
        kind, pkgs, args = _normalize_any(env)
        blob = "\n".join(
            [kind] + pkgs + ["--"] + args + [sys.version.split()[0]]
        ).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def env_dir(self, key: str) -> str:
        return os.path.join(self.base_dir, key)

    @staticmethod
    def _build_cmd(kind: str, tmp: str, pkgs, args) -> List[str]:
        if kind == "pip":
            return [
                sys.executable,
                "-m",
                "pip",
                "install",
                "--target",
                tmp,
                "--disable-pip-version-check",
                "--no-input",
                *args,
                *pkgs,
            ]
        if kind == "uv":
            uv = shutil.which("uv")
            if uv is None:
                raise RuntimeError(
                    "runtime_env['uv'] requested but no 'uv' binary on PATH"
                )
            # same --target layout as pip (the worker shadows
            # site-packages identically); --python pins resolution to the
            # cluster interpreter (uv.py reference semantics)
            return [
                uv,
                "pip",
                "install",
                "--target",
                tmp,
                "--python",
                sys.executable,
                *args,
                *pkgs,
            ]
        if kind == "conda":
            conda = conda_binary()
            if conda is None:
                raise RuntimeError(
                    "runtime_env['conda'] requested but no conda/mamba/"
                    "micromamba binary found (set RAY_TPU_CONDA_BINARY)"
                )
            return [conda, "create", "--yes", "-p", tmp, *args, *pkgs]
        raise ValueError(f"unknown env kind {kind!r}")

    def ensure(self, env) -> Tuple[str, str]:
        """Return (key, env_dir), building the environment if it doesn't
        exist yet. ``env`` is a {"pip"/"uv"/"conda": spec} slice or a bare
        pip spec. Concurrent callers for one key serialize on a file
        lock; only the winner runs the builder."""
        kind, pkgs, args = _normalize_any(env)
        key = self.key_of(env)
        env_dir = self.env_dir(key)
        marker = env_dir + ".built"
        with self._lock:  # serialized vs gc(): marker+dir vanish atomically
            if os.path.exists(marker):
                return key, env_dir
        lock_path = env_dir + ".lock"
        with open(lock_path, "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                if os.path.exists(marker):  # built while we waited
                    return key, env_dir
                if kind == "conda":
                    # conda embeds its absolute creation prefix (shebangs,
                    # prefix-replaced files) — a build-at-tmp-then-rename
                    # env is broken by design, so build in place; the
                    # marker (written only on success, under the flock) is
                    # what distinguishes a finished env from a partial one
                    shutil.rmtree(env_dir, ignore_errors=True)
                    target = env_dir
                else:
                    target = env_dir + ".tmp"
                    shutil.rmtree(target, ignore_errors=True)
                cmd = self._build_cmd(kind, target, pkgs, args)
                proc = subprocess.run(
                    cmd,
                    capture_output=True,
                    text=True,
                    timeout=self.BUILD_TIMEOUT_S,
                    env={**os.environ, "PIP_NO_COLOR": "1"},
                )
                if proc.returncode != 0:
                    shutil.rmtree(target, ignore_errors=True)
                    raise RuntimeError(
                        f"{kind} env build failed (key {key}): "
                        + (proc.stderr or proc.stdout)[-1500:]
                    )
                if target != env_dir:
                    shutil.rmtree(env_dir, ignore_errors=True)
                    os.replace(target, env_dir)
                with open(marker, "w") as mf:
                    mf.write(kind + "\n" + " ".join(pkgs))
                return key, env_dir
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

    @staticmethod
    def interpreter_for(kind: str, env_dir: str) -> str:
        """The python that runs a worker bound to this env: conda envs
        bring their own; pip/uv --target dirs ride the base interpreter
        with sys.path shadowing."""
        if kind == "conda":
            return os.path.join(env_dir, "bin", "python")
        return sys.executable

    # ------------------------------------------------------------------
    def acquire(self, key: str) -> None:
        with self._lock:
            self._refs[key] = self._refs.get(key, 0) + 1

    def release(self, key: str) -> None:
        with self._lock:
            c = self._refs.get(key, 0) - 1
            if c <= 0:
                self._refs.pop(key, None)
            else:
                self._refs[key] = c

    def gc(self) -> int:
        """Remove unreferenced environments beyond max_cached, oldest
        first (the reference GCs per-env on last-actor-exit; a small LRU
        cache keeps warm envs for repeat jobs). Returns removed count.

        The lock covers only the cheap part — liveness read, marker
        unlink, and an atomic rename of each doomed dir to a .tmp name —
        so an acquire() racing the sweep either lands before the read
        (env survives) or sees the env already gone and rebuilds. The
        slow recursive deletes run after the lock is released (rmtree of
        a large env must not stall pip dispatch node-wide)."""
        doomed: List[str] = []
        with self._lock:
            live = set(self._refs)
            envs = []
            try:
                for name in os.listdir(self.base_dir):
                    p = os.path.join(self.base_dir, name)
                    if not os.path.isdir(p):
                        continue
                    if name.endswith(".gc.tmp"):
                        # grave from a sweep interrupted by process death:
                        # always finish the burial
                        doomed.append(p)
                    elif not name.endswith(".tmp"):
                        envs.append((os.path.getmtime(p), name))
            except OSError:
                return 0
            envs.sort()
            removed = 0
            excess = len(envs) - self.max_cached
            for _, name in envs:
                if excess <= removed or name in live:
                    continue
                for suffix in (".built", ".lock"):
                    try:
                        os.unlink(os.path.join(self.base_dir, name + suffix))
                    except OSError:
                        pass
                grave = os.path.join(
                    self.base_dir, f"{name}.{os.getpid()}.gc.tmp"
                )
                try:
                    os.rename(os.path.join(self.base_dir, name), grave)
                except OSError:
                    continue
                doomed.append(grave)
                removed += 1
        for grave in doomed:
            shutil.rmtree(grave, ignore_errors=True)
        return removed
