"""Multi-process cluster harness for tests and local clusters.

The analog of the reference's ``ray.cluster_utils.Cluster``
(/root/reference/python/ray/cluster_utils.py:137): the head runs in-process
(so tests can reach its metrics/state directly), and every ``add_node``
launches a REAL node-agent subprocess with its own resource spec, worker
subprocesses, and shared-memory store — multi-node scheduling, object
transfer, and failure handling are exercised across genuine process
boundaries on one machine.
"""
from __future__ import annotations

import json
import subprocess
import sys
import time
from typing import Dict, List, Optional

from .client import RemoteRuntime
from .head import HeadServer
from .rpc import RpcClient, RpcError


class Cluster:
    def __init__(
        self,
        use_device_scheduler: Optional[bool] = None,
        dashboard: bool = False,
        persist_path: Optional[str] = None,
    ):
        self._dashboard = dashboard
        self._persist_path = persist_path
        self._use_device_scheduler = use_device_scheduler
        self.head = HeadServer(
            use_device_scheduler=use_device_scheduler,
            dashboard_port=0 if dashboard else None,
            persist_path=persist_path,
        )
        self.address = self.head.address
        self._agents: Dict[str, subprocess.Popen] = {}
        self._counter = 0
        # warm-standby head (start_standby/promote/kill_head): failover
        # harness for tests and chaos plans
        self.standby = None
        self._dead_heads: List[HeadServer] = []

    def restart_head(self) -> None:
        """Kill and restart only the head on the same port (GCS fault
        tolerance: agents and their actors keep running, re-register, and
        persisted state reloads)."""
        port = int(self.address.rsplit(":", 1)[1])
        self.head.shutdown(stop_agents=False)
        time.sleep(0.3)
        self.head = HeadServer(
            port=port,
            use_device_scheduler=self._use_device_scheduler,
            dashboard_port=0 if self._dashboard else None,
            persist_path=self._persist_path,
        )
        assert self.head.address == self.address

    # ------------------------------------------------------------------
    # replicated control plane (standby.py): warm-standby failover
    # ------------------------------------------------------------------
    def start_standby(self, auto_promote: bool = True):
        """Start (or replace) a warm standby tailing this cluster's
        leader. With ``auto_promote`` it detects leader death via the
        strike-based watch loop and promotes itself onto the leader's
        port; ``cluster.head`` swaps to the promoted instance."""
        from .standby import StandbyHead

        if self.standby is not None:
            self.standby.shutdown()
        self.standby = StandbyHead(
            self.address,
            persist_path=self._persist_path,
            auto_promote=auto_promote,
            use_device_scheduler=self._use_device_scheduler,
        )
        self.standby.on_promoted = self._adopt_head
        return self.standby

    def _adopt_head(self, head: HeadServer) -> None:
        self.head = head

    def kill_head(self) -> None:
        """SIGKILL-equivalent for the in-process leader: the RPC
        listener drops mid-flight, no final snapshot is flushed, no
        agent is told anything — and the persistence dir stays intact
        for the standby. (The head runs in-process so tests can reach
        its tables; an os.kill would take the test with it.)"""
        head = self.head
        head._shutdown = True
        with head._cond:
            head._cond.notify_all()
        head._repl.stop()
        head._server.stop(grace=0)
        if head._pipeline is not None:
            try:
                head._pipeline.stop()
            except Exception:  # noqa: BLE001 - corpse hygiene only
                pass
        head._dispatch_pool.shutdown(wait=False, cancel_futures=True)
        try:
            head.jobs.shutdown()
        except Exception:  # noqa: BLE001
            pass
        # close channels so the corpse's breaker callbacks never fire
        # into dead state (in-process analog of the kernel reaping fds)
        with head._lock:
            clients = list(head._clients.values())
        for client in clients:
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass
        self._dead_heads.append(head)

    def promote(self, timeout: float = 30.0) -> HeadServer:
        """Promote the standby (or wait out its in-flight
        auto-promotion) and adopt the new head."""
        if self.standby is None:
            raise RuntimeError("no standby started (start_standby first)")
        if self.standby.promoted is None and not self.standby.auto_promote:
            self.standby.promote()
        head = self.standby.wait_promoted(timeout=timeout)
        if head is None:
            raise TimeoutError(
                f"standby did not promote within {timeout}s"
            )
        self.head = head
        return head

    def add_node(
        self,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        num_workers: int = 2,
        wait: bool = True,
        store_capacity: int = 1 << 28,
    ) -> str:
        resources = dict(resources or {"CPU": 4.0})
        resources.setdefault("memory", float(4 << 30))
        resources.setdefault("object_store_memory", float(1 << 30))
        self._counter += 1
        node_id = f"node{self._counter:03d}" + "0" * 9
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu.cluster.agent",
                "--head",
                self.address,
                "--resources",
                json.dumps(resources),
                "--labels",
                json.dumps(labels or {}),
                "--num-workers",
                str(num_workers),
                "--node-id",
                node_id,
                "--store-capacity",
                str(store_capacity),
            ],
        )
        self._agents[node_id] = proc
        if wait:
            self.wait_for_nodes(len(self._agents))
        return node_id

    def wait_for_nodes(self, count: int, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            alive = sum(1 for n in self.head.nodes.values() if n.alive)
            if alive >= count:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"cluster did not reach {count} live nodes in {timeout}s"
        )

    def kill_node(self, node_id: str) -> None:
        """Hard-kill an agent process (RayletKiller chaos analog,
        _private/test_utils.py:1408). The head's health checks notice."""
        proc = self._agents.get(node_id)
        if proc is not None:
            proc.kill()
            proc.wait(timeout=10)
            # drop the corpse so add_node's wait target counts only
            # launched-and-living agents (killing every replica of an
            # object then adding a recovery node must not wait forever
            # for the dead ones to come back)
            self._agents.pop(node_id, None)

    def drain_node(
        self, node_id: str, deadline_s: Optional[float] = None
    ) -> bool:
        """Graceful retirement (PR 19 drain-ahead): mark the node
        draining at the head (zero advertised capacity, drain-ahead
        migration moves its leased work), then terminate the agent
        process once drained or at the deadline. Returns False for
        unknown nodes."""
        from ray_tpu.config import cfg

        if node_id not in self._agents:
            return False
        if deadline_s is None:
            deadline_s = float(cfg.elastic_drain_deadline_s)
        if not self.head.begin_node_drain(node_id, deadline_s=deadline_s):
            return False
        try:
            self.head.migrate_node_leases(node_id)
        except Exception:  # noqa: BLE001 - best-effort ahead of the kill
            pass
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if self.head.node_drained(node_id):
                break
            time.sleep(0.05)
        proc = self._agents.pop(node_id, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        self.head.finish_node_drain(node_id, retire=True)
        return True

    def attach_elasticity_provider(
        self,
        resources: Optional[Dict[str, float]] = None,
        num_workers: int = 1,
        max_nodes: int = 8,
    ) -> "ClusterProvider":
        """Wire this harness in as the elasticity controller's agent
        lifecycle: provisions become real ``add_node`` subprocesses,
        retirements real drains. Returns the provider."""
        provider = ClusterProvider(
            self,
            resources=resources,
            num_workers=num_workers,
            max_nodes=max_nodes,
        )
        self.head._elasticity.attach_provider(provider)
        return provider

    # ------------------------------------------------------------------
    # chaos fault surface (ray_tpu.chaos rides these)
    # ------------------------------------------------------------------
    def agent_address(self, node_id: str) -> Optional[str]:
        info = self.head.nodes.get(node_id)
        return info.address if info is not None else None

    def partition_node(self, node_id: str) -> bool:
        """Blackhole the control plane's path TO this node (one-way
        partition): every head->agent RPC fails at transport level, the
        per-peer circuit breaker opens within its window, and the
        node-unreachable callback feeds the health path. The agent itself
        keeps running — on heal it re-registers and rejoins."""
        from .rpc import FAULTS

        addr = self.agent_address(node_id)
        if addr is None:
            return False
        FAULTS.blackhole(addr)
        return True

    def heal_node(self, node_id: str) -> bool:
        from .rpc import FAULTS

        addr = self.agent_address(node_id)
        if addr is None:
            return False
        FAULTS.heal(addr)
        return True

    def set_node_delay(self, node_id: str, seconds: float) -> bool:
        """Straggler injection: every head->agent RPC to this node waits
        ``seconds`` before hitting the wire (delay ramps come from the
        chaos plan calling this repeatedly)."""
        from .rpc import FAULTS

        addr = self.agent_address(node_id)
        if addr is None:
            return False
        FAULTS.set_delay(addr, seconds)
        return True

    def heal_all(self) -> None:
        from .rpc import FAULTS

        FAULTS.clear()

    def client(self) -> RemoteRuntime:
        return RemoteRuntime(self.address)

    def shutdown(self) -> None:
        # standby first: its watch loop must not misread the leader's
        # clean shutdown as a death and promote into the teardown
        if self.standby is not None:
            self.standby.shutdown()
            self.standby = None
        self.head.shutdown()
        for proc in self._agents.values():
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + 5
        for proc in self._agents.values():
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
        self._agents.clear()


class ClusterProvider:
    """The elasticity controller's node lifecycle against a local
    :class:`Cluster` — the in-process analog of a cloud provider's
    instance API. ``create_node`` launches a real agent subprocess;
    ``drain_node``/``terminate_node`` retire one. ``max_nodes`` bounds
    runaway provisioning the way a cloud quota would."""

    def __init__(
        self,
        cluster: Cluster,
        resources: Optional[Dict[str, float]] = None,
        num_workers: int = 1,
        max_nodes: int = 8,
    ):
        self.cluster = cluster
        self.resources = dict(resources or {"CPU": 2.0})
        self.num_workers = num_workers
        self.max_nodes = max_nodes
        self.created: List[str] = []
        self.terminated: List[str] = []

    def node_template(self) -> Dict[str, float]:
        return dict(self.resources)

    def create_node(self) -> Optional[str]:
        if len(self.cluster._agents) >= self.max_nodes:
            return None
        node_id = self.cluster.add_node(
            resources=dict(self.resources),
            num_workers=self.num_workers,
            wait=False,
        )
        self.created.append(node_id)
        return node_id

    def drain_node(self, node_id: str, deadline_s: float) -> bool:
        ok = self.cluster.drain_node(node_id, deadline_s=deadline_s)
        if ok:
            self.terminated.append(node_id)
        return ok

    def terminate_node(self, node_id: str) -> bool:
        if node_id not in self.cluster._agents:
            return False
        self.cluster.kill_node(node_id)
        self.terminated.append(node_id)
        return True
