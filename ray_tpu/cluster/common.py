"""Shared control-plane message types for the distributed runtime.

These are the moral equivalent of the reference's protobuf messages
(/root/reference/src/ray/protobuf/common.proto, gcs_service.proto,
node_manager.proto) — dataclasses shipped over the generic gRPC layer.
"""
from __future__ import annotations

import os
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.config import cfg

# Values at or below this ride inline through the head's object table
# (max_direct_call_object_size analog, ray_config_def.h:218).
INLINE_OBJECT_MAX = cfg.inline_object_max

# Resource report cadence (raylet_report_resources_period_milliseconds=100,
# ray_config_def.h:65). Health detection reads cfg.health_timeout_s /
# cfg.health_miss_threshold LIVE in head._health_loop — no import-time
# binding, so runtime env overrides (tests, chaos soaks) take effect.
REPORT_PERIOD_S = cfg.report_period_s


def new_id() -> str:
    from ray_tpu._ids import rand_hex

    return rand_hex(8)  # buffered urandom: no syscall per id


from ray_tpu.util.metrics import Histogram as _Histogram

# execution-plane hot-path decomposition, SAMPLED 1-in-64 per call site
# (a locked observe per item would be per-item Python on the very path
# this histogram exists to prove clean): serialize = payload framing,
# enqueue = lease-manager/channel hand-off, wire = one window's RPC send
# (per-item share), execute = worker-side run, result = owner-side
# delivery of a merged result batch (per-item share). Shared here so the
# owner (client.py) and worker observe the same instrument name.
DISPATCH_OVERHEAD_US = _Histogram(
    "dispatch_overhead_us",
    "Per-stage dispatch overhead decomposition (sampled), microseconds.",
    boundaries=[1, 5, 10, 25, 50, 100, 250, 500, 1000, 5000, 25000, 100000],
    label_names=("stage",),
)
_sample_tick = 0


def dispatch_sampled() -> bool:
    global _sample_tick
    _sample_tick = (_sample_tick + 1) & 63
    return _sample_tick == 0


def stream_item_id(task_id: str, index: int) -> str:
    """Deterministic object id for item ``index`` of a streaming-generator
    task. Determinism is the recovery story: a retried generator re-seals
    the SAME ids, so refs a consumer already iterated resolve to the
    re-executed copies (the reference derives generator return ids from
    task id + return index the same way)."""
    import hashlib

    return hashlib.blake2b(
        f"{task_id}:{index}".encode(), digest_size=14
    ).hexdigest()


@dataclass
class NodeInfo:
    node_id: str
    address: str  # agent RPC address
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    # actors whose workers this agent still hosts, as
    # {"actor_id", "name", "max_restarts"} — lets a restarted head re-attach
    # live actors (GCS FT resubscribe analog, gcs_init_data.cc)
    hosted_actors: List[dict] = field(default_factory=list)
    # (object_id, size) inventory of this node's store — a restarted head
    # re-seeds its object directory from these, so refs minted before the
    # restart resolve (the directory died with the old head; the
    # bytes didn't)
    stored_objects: List[Tuple[str, int]] = field(default_factory=list)
    # task-lease ids whose worker this agent still has pinned — a restarted
    # head reconciles these against its (possibly unpersisted) lease table
    # and releases any it no longer tracks, so leased workers never stay
    # pinned to a lease the control plane forgot
    held_task_leases: List[str] = field(default_factory=list)
    # cross-node data plane (transport.py): where this node's stripe
    # server listens, and the per-incarnation auth token peers must
    # present on the data-path handshake. The head hands both out in
    # peer-link grants; an agent restart mints a fresh token, so stale
    # cached links are rejected and re-granted automatically.
    data_endpoint: str = ""
    net_token: str = ""


@dataclass
class LeaseRequest:
    """A task / actor-creation / actor-method lease (LeaseSpecification
    analog, src/ray/common/lease/)."""

    task_id: str
    name: str
    payload: bytes  # cloudpickled (func, args, kwargs); (args, kwargs) when fn_blob set
    return_ids: List[str]
    resources: Dict[str, float]
    # worker_lease: not a task — a request to pin one worker + this
    # resource shape for an owner's direct task dispatch (task leases)
    kind: str = "task"  # task | actor_creation | actor_method | worker_lease
    actor_id: Optional[str] = None
    max_retries: int = 3
    retry_exceptions: bool = False
    attempt: int = 0
    strategy: Any = None
    runtime_env: Optional[dict] = None
    # set by the head when routing:
    target_node: Optional[str] = None
    pg_reservation: Optional[Tuple[str, int]] = None  # (pg_id, bundle_idx)
    # actor_creation only: {"name", "max_restarts"} so the hosting agent can
    # re-describe its actors to a restarted head
    actor_meta: Optional[dict] = None
    # --- distributed refcounting (reference_counter.h analog) ---
    # every ObjectRef serialized into the payload (nested included): the
    # head pins these for the lease's lifetime (args must outlive dispatch)
    arg_ids: List[str] = field(default_factory=list)
    # TOP-LEVEL ObjectRef args only: the set the worker resolves before
    # running, i.e. what dependency-aware dispatch waits on. Nested refs
    # reach user code unresolved (reference semantics) and must NOT gate
    # dispatch — a task may exist precisely to unblock the object a nested
    # ref points at.
    deps: List[str] = field(default_factory=list)
    # submitting process's holder id: the initial owner of the return ids
    client_id: str = ""
    # distributed trace context (util/tracing.py); rides the wire so every
    # hop's lifecycle events share one trace id
    trace: Optional[dict] = None
    # plain tasks only: the function pickled SEPARATELY from (args, kwargs)
    # so the client pickles it once per function object and executors
    # deserialize it once per (worker, fn_id) — the reference exports a
    # remote function's pickle once at first submission for the same
    # reason (function_manager export path) instead of re-pickling per
    # call. fn_cache=False (fn closes over ObjectRefs) keeps per-call
    # deserialization so ref lifetimes stay per-execution.
    fn_blob: Optional[bytes] = None
    fn_id: str = ""
    fn_cache: bool = True
    # num_returns="streaming": the executor yields N results incrementally;
    # each is sealed as its own object under a DETERMINISTIC id
    # (stream_item_id), the head tracks per-stream item order/done state,
    # and the caller iterates an ObjectRefGenerator
    # (object_ref_generator.py / _raylet.pyx:246 analog)
    streaming: bool = False

    def __getstate__(self):
        # head-side scheduling memos (e.g. _req_cache) never ride the wire
        return {
            k: v for k, v in self.__dict__.items() if not k.startswith("_")
        }


@dataclass
class SealInfo:
    """Worker -> agent -> head: an object became available."""

    object_id: str
    node_id: str
    size: int = 0
    inline_value: Optional[bytes] = None  # pickled value if small
    is_error: bool = False
    error: Optional[bytes] = None  # pickled exception
    # ObjectRefs serialized inside the sealed value: the head pins them
    # while this object is alive (nested-ref ownership,
    # reference_counter.h AddNestedObjectIds)
    contained_ids: List[str] = field(default_factory=list)
    # direct actor calls: the caller that owns the return object. The head
    # registers it as a holder when the seal lands (the lease path does
    # this at submission; direct calls never create a lease).
    owner: Optional[str] = None


@dataclass
class WalShipBatch:
    """Leader -> standby replication batch (``ReplWal``): a contiguous
    run of the leader's persistence stream. ``records`` items are
    ``("wal", record)`` WAL records or ``("snap", snapshot)`` barriers,
    sequence-numbered from ``start_seq``; a bootstrap/re-sync batch
    instead carries a full ``snapshot`` at position ``snap_seq``. The
    standby replies ``{"applied_to": seq}``, ``{"resync_from": seq}``
    on a gap, or ``{"fenced": epoch}`` once it has promoted — the reply
    that fences a deposed leader off its own shipping stream."""

    epoch: int
    leader: str
    start_seq: int
    records: List[Tuple[str, Any]] = field(default_factory=list)
    snapshot: Optional[dict] = None
    snap_seq: int = 0


@dataclass
class NodeReport:
    """Agent -> head periodic report (RaySyncer RESOURCE_VIEW analog,
    src/ray/ray_syncer/ray_syncer.h:81)."""

    node_id: str
    available: Dict[str, float]
    seals: List[SealInfo] = field(default_factory=list)
    finished_leases: List[str] = field(default_factory=list)
    version: int = 0


@dataclass
class ActorInfo:
    actor_id: str
    name: Optional[str]
    node_id: Optional[str] = None
    address: Optional[str] = None  # agent address hosting the actor
    state: str = "PENDING"  # PENDING | ALIVE | RESTARTING | DEAD
    class_name: str = ""
    max_restarts: int = 0
    num_restarts: int = 0
    # lifetime="detached": owned by the head, survives its creating
    # driver's disconnect and head restarts; killed only explicitly
    # (reference actor.py:1875 detached lifetimes). Default (None):
    # reaped when the owning client disconnects.
    lifetime: Optional[str] = None
    owner_client: str = ""
