"""Pluggable head-state persistence: snapshot store + write-ahead log.

Analog of the reference's GCS storage layer
(/root/reference/src/ray/gcs/store_client/ — pluggable Redis/in-memory
backends) plus write-ahead durability for registrations that land between
snapshot ticks: every durable mutation (KV write, actor registration) is
appended to the WAL immediately; a snapshot supersedes and truncates it.
Recovery = load snapshot, then replay the WAL.

``FilePersistence`` is the built-in backend (length-prefixed pickled
records; atomic snapshot swap). Anything implementing the same four
methods can be passed to ``HeadServer(persist_backend=...)`` — e.g. a
Redis- or cloud-bucket-backed store.
"""
from __future__ import annotations

import logging
import os
import pickle
import struct
import threading
from typing import Any, List, Optional, Tuple

logger = logging.getLogger("ray_tpu.cluster.persistence")


class FilePersistence:
    """Snapshot at ``path``, WAL at ``path + '.wal'``."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.wal_path = path + ".wal"
        self.fsync = fsync
        self._lock = threading.Lock()
        self._wal_f = None

    # -- snapshot ------------------------------------------------------
    def load(self) -> Optional[dict]:
        try:
            with open(self.path, "rb") as f:
                return pickle.load(f)
        except FileNotFoundError:
            return None
        except Exception:  # noqa: BLE001 - corrupt snapshot: start fresh
            logger.exception("could not load snapshot; starting fresh")
            return None

    def save_snapshot(self, snap: dict) -> None:
        """Atomic snapshot swap; the WAL it supersedes is truncated."""
        with self._lock:
            tmp = f"{self.path}.{os.getpid()}.{threading.get_ident()}.tmp"
            with open(tmp, "wb") as f:
                pickle.dump(snap, f)
                if self.fsync:
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self._truncate_wal_locked()

    # -- write-ahead log -----------------------------------------------
    def wal_append(self, record: Tuple[Any, ...]) -> None:
        with self._lock:
            if self._wal_f is None:
                self._wal_f = open(self.wal_path, "ab")
            blob = pickle.dumps(record)
            self._wal_f.write(struct.pack("<I", len(blob)) + blob)
            self._wal_f.flush()
            if self.fsync:
                os.fsync(self._wal_f.fileno())

    def wal_replay(self) -> List[Tuple[Any, ...]]:
        out: List[Tuple[Any, ...]] = []
        try:
            with open(self.wal_path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return out
        off = 0
        while off + 4 <= len(data):
            (n,) = struct.unpack_from("<I", data, off)
            off += 4
            if off + n > len(data):
                break  # torn tail write: ignore the partial record
            try:
                out.append(pickle.loads(data[off : off + n]))
            except Exception:  # noqa: BLE001 - skip corrupt record
                logger.warning("skipping corrupt WAL record at offset %d", off)
            off += n
        return out

    def _truncate_wal_locked(self) -> None:
        if self._wal_f is not None:
            try:
                self._wal_f.close()
            except OSError:
                pass
            self._wal_f = None
        try:
            os.unlink(self.wal_path)
        except OSError:
            pass

    def close(self) -> None:
        with self._lock:
            if self._wal_f is not None:
                try:
                    self._wal_f.close()
                except OSError:
                    pass
                self._wal_f = None


class MemPersistence:
    """In-memory backend (tests, standbys with no disk): same four-method
    contract as :class:`FilePersistence`, zero I/O."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._snap: Optional[dict] = None
        self._wal: List[Tuple[Any, ...]] = []

    def load(self) -> Optional[dict]:
        with self._lock:
            return self._snap

    def save_snapshot(self, snap: dict) -> None:
        with self._lock:
            self._snap = snap
            self._wal.clear()

    def wal_append(self, record: Tuple[Any, ...]) -> None:
        with self._lock:
            self._wal.append(record)

    def wal_replay(self) -> List[Tuple[Any, ...]]:
        with self._lock:
            return list(self._wal)

    def close(self) -> None:
        pass


class HandoffPersistence:
    """Promotion handoff: a warm standby's continuously-replayed tables
    become the FIRST load of the promoted head — no disk read, no WAL
    scan (the whole point of WAL shipping: promotion is an epoch bump +
    listener bind, not a replay-from-disk). Every subsequent write
    (snapshots, WAL appends) delegates to the real backend so the
    promoted head persists normally from its first dirty tick."""

    def __init__(self, inner: Any, snapshot: dict):
        self._inner = inner
        self._handoff = snapshot

    def load(self) -> Optional[dict]:
        # NOT consumed on read: promotion retries its listener bind
        # (TIME_WAIT on the dead leader's port) by constructing a fresh
        # HeadServer against this same backend, and each attempt loads
        # AGAIN — a one-shot load would hand the retry empty tables
        return self._handoff

    def wal_replay(self) -> List[Tuple[Any, ...]]:
        # the standby already merged every shipped record into the
        # handoff snapshot; the on-disk WAL (if any) predates it
        return []

    def save_snapshot(self, snap: dict) -> None:
        self._inner.save_snapshot(snap)

    def wal_append(self, record: Tuple[Any, ...]) -> None:
        self._inner.wal_append(record)

    def close(self) -> None:
        self._inner.close()
