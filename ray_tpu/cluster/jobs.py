"""Job submission: run driver entrypoints against the cluster.

The analog of the reference's job-submission stack
(/root/reference/python/ray/dashboard/modules/job/: REST API +
JobSubmissionClient at sdk.py:36, with a JobSupervisor running the
entrypoint). Here the head's JobManager launches each entrypoint as a
subprocess with ``RAY_TPU_HEAD_ADDRESS`` set, so any ``ray_tpu`` API call
in the script auto-connects as a driver; stdout/stderr are captured per
job and served back over RPC (and the dashboard).
"""
from __future__ import annotations

import os
import shlex
import subprocess
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .common import new_id
from .rpc import RpcClient

# terminal + live states (reference JobStatus enum,
# dashboard/modules/job/common.py)
PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = PENDING
    start_time: float = 0.0
    end_time: float = 0.0
    return_code: Optional[int] = None
    log_path: str = ""
    runtime_env: Optional[dict] = None
    metadata: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "job_id": self.job_id,
            "entrypoint": self.entrypoint,
            "status": self.status,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "return_code": self.return_code,
            "metadata": dict(self.metadata),
        }


class JobManager:
    """Head-side job lifecycle (JobSupervisor analog, but a plain
    subprocess on the head host rather than an actor)."""

    def __init__(
        self,
        head_address: Optional[str],
        log_dir: Optional[str] = None,
        on_change=None,
    ):
        self.head_address = head_address
        self.log_dir = log_dir or os.path.join(
            tempfile.gettempdir(), "ray_tpu_job_logs"
        )
        os.makedirs(self.log_dir, exist_ok=True)
        self._jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self._on_change = on_change or (lambda: None)

    def snapshot(self) -> List[dict]:
        """Durable job table rows (head persistence)."""
        with self._lock:
            return [
                {**i.to_dict(), "log_path": i.log_path}
                for i in self._jobs.values()
            ]

    def restore(self, row: dict) -> None:
        """Re-load a persisted job row after a head restart. Jobs that were
        live have lost their subprocess — mark them failed."""
        info = JobInfo(
            job_id=row["job_id"],
            entrypoint=row["entrypoint"],
            status=row["status"],
            start_time=row.get("start_time", 0.0),
            end_time=row.get("end_time", 0.0),
            return_code=row.get("return_code"),
            log_path=row.get("log_path", ""),
            metadata=dict(row.get("metadata", {})),
        )
        if info.status in (PENDING, RUNNING):
            info.status = FAILED
            info.end_time = time.time()
        with self._lock:
            self._jobs[info.job_id] = info

    def submit(
        self,
        entrypoint: str,
        runtime_env: Optional[dict] = None,
        submission_id: Optional[str] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        if self.head_address is None:
            # head is mid-bootstrap (RPC server bound, address not yet
            # published to us) — a clean retryable error beats spawning a
            # job with RAY_TPU_HEAD_ADDRESS unset.
            raise RuntimeError("head is not ready to accept jobs yet")
        job_id = submission_id or f"raytpu-job-{new_id()}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id} already exists")
            info = JobInfo(
                job_id=job_id,
                entrypoint=entrypoint,
                runtime_env=runtime_env,
                metadata=dict(metadata or {}),
                log_path=os.path.join(self.log_dir, f"{job_id}.log"),
            )
            self._jobs[job_id] = info
        self._on_change()
        threading.Thread(
            target=self._run, args=(info,), name=f"job-{job_id}", daemon=True
        ).start()
        return job_id

    def _run(self, info: JobInfo) -> None:
        env = dict(os.environ)
        env["RAY_TPU_HEAD_ADDRESS"] = self.head_address
        env["RAY_TPU_JOB_ID"] = info.job_id
        # entrypoints run from arbitrary cwds: make the framework importable
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        existing = env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + existing if existing else "")
            )
        renv = info.runtime_env or {}
        for k, v in (renv.get("env_vars") or {}).items():
            env[k] = str(v)
        cwd = renv.get("working_dir") or None
        info.start_time = time.time()
        try:
            with open(info.log_path, "wb") as log:
                proc = subprocess.Popen(
                    shlex.split(info.entrypoint),
                    env=env,
                    cwd=cwd,
                    stdout=log,
                    stderr=subprocess.STDOUT,
                )
                with self._lock:
                    # submit() raced a stop(): honor it
                    if info.status == STOPPED:
                        proc.kill()
                        proc.wait()  # reap; no zombie for the head lifetime
                        return
                    info.status = RUNNING
                    self._procs[info.job_id] = proc
            rc = proc.wait()
            with self._lock:
                info.return_code = rc
                info.end_time = time.time()
                if info.status != STOPPED:
                    info.status = SUCCEEDED if rc == 0 else FAILED
            self._on_change()
        except Exception as exc:  # noqa: BLE001 - entrypoint must not kill head
            with self._lock:
                info.status = FAILED
                info.end_time = time.time()
            try:
                with open(info.log_path, "ab") as log:
                    log.write(f"\njob manager error: {exc!r}\n".encode())
            except OSError:
                pass
        finally:
            with self._lock:
                self._procs.pop(info.job_id, None)

    def stop(self, job_id: str) -> bool:
        with self._lock:
            info = self._jobs.get(job_id)
            proc = self._procs.get(job_id)
            if info is None:
                return False
            if info.status in (SUCCEEDED, FAILED, STOPPED):
                return False
            info.status = STOPPED
            info.end_time = time.time()
        self._on_change()
        if proc is not None:
            try:
                proc.terminate()
                try:
                    proc.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    proc.kill()
            except OSError:
                pass
        return True

    def status(self, job_id: str) -> dict:
        with self._lock:
            info = self._jobs.get(job_id)
            if info is None:
                raise ValueError(f"unknown job {job_id}")
            return info.to_dict()

    def logs(self, job_id: str) -> str:
        with self._lock:
            info = self._jobs.get(job_id)
        if info is None:
            raise ValueError(f"unknown job {job_id}")
        try:
            with open(info.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def list(self) -> List[dict]:
        with self._lock:
            return [i.to_dict() for i in self._jobs.values()]

    def shutdown(self) -> None:
        with self._lock:
            procs = list(self._procs.values())
        for p in procs:
            try:
                p.kill()
            except OSError:
                pass


class JobSubmissionClient:
    """Driver-side client (reference sdk.py:36 parity surface)."""

    def __init__(self, address: str):
        self._client = RpcClient(address)
        self._client.call("Ping", timeout=10.0, retries=10, retry_interval=0.2)

    def submit_job(
        self,
        *,
        entrypoint: str,
        runtime_env: Optional[dict] = None,
        submission_id: Optional[str] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        return self._client.call(
            "SubmitJob",
            {
                "entrypoint": entrypoint,
                "runtime_env": runtime_env,
                "submission_id": submission_id,
                "metadata": metadata,
            },
        )

    def get_job_status(self, job_id: str) -> str:
        return self._client.call("JobStatus", {"job_id": job_id})["status"]

    def get_job_info(self, job_id: str) -> dict:
        return self._client.call("JobStatus", {"job_id": job_id})

    def get_job_logs(self, job_id: str) -> str:
        return self._client.call("JobLogs", {"job_id": job_id})

    def list_jobs(self) -> List[dict]:
        return self._client.call("ListJobs")

    def stop_job(self, job_id: str) -> bool:
        return self._client.call("StopJob", {"job_id": job_id})

    def wait_until_finished(
        self, job_id: str, timeout: float = 300.0, poll: float = 0.25
    ) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in (SUCCEEDED, FAILED, STOPPED):
                return status
            time.sleep(poll)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")
