"""Fused owner-side submit/result event loop (the execution-plane hot
path's control thread).

Before this module, every ``_TaskLeaseChannel`` (batched lease windows),
every ``_DirectActorChannel`` (direct actor pushes), and the
direct-results delivery each ran their own thread, each parking on its
own condition variable on a 0.25–1 s poll — per-channel wakeups, one
lock hop per item, and O(channels) idle threads. This module collapses
them into ONE event loop per runtime:

- **sources** register with ``step(now) -> next_deadline``; the loop
  calls ``step`` when a source is woken (``wake``) or its timer expires.
  ``step`` is non-blocking by contract: it inspects state, forms a
  whole batch, and offloads any RPC to the bounded sender pool.
- **one wake per window**: ``wake`` marks the source ready and notifies
  the single loop condition variable; N submissions racing in while the
  loop is busy coalesce into one ``step`` that drains them all.
- **senders**: blocking RPCs (lease windows, direct pushes, probes)
  run on a small shared pool instead of per-channel threads; a source
  is guarded by its own in-flight flag so ordering within a channel is
  preserved (at most one action in flight per source).
- **result sink**: incoming ``DirectResults`` RPC batches enqueue and
  wake the loop; the sink's ``step`` drains EVERY queued batch in one
  pass under one lock acquisition (batch-at-once result delivery).

The reference's shape is core_worker's C++ submit loop: the Python that
remains per item is the user-visible serialize; everything else is
per-window.
"""
from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("ray_tpu.cluster.event_loop")

# process-wide registry (weak) so observability surfaces can report
# occupancy for every live loop without plumbing references around
import weakref

_LOOPS: "weakref.WeakSet" = weakref.WeakSet()


def loop_stats() -> List[dict]:
    return [lp.stats() for lp in list(_LOOPS)]


def publish_dark_plane() -> None:
    """Sync every dark-plane accumulator (plain-int wire counters, the
    shm counter page shared with wire.cc/net.cc, compiled-pipeline
    slots, ring fill levels) into the typed metrics registry. Called
    from observability ticks — agent report loop, head scrape, DebugState
    — never from a hot path; from there the federation ships them to the
    head scrape."""
    from ray_tpu.cluster import serialization as wire_mod

    wire_mod.publish_wire_metrics()
    try:
        from ray_tpu.cluster import device_plane

        device_plane.publish_device_metrics()
    except Exception:  # noqa: BLE001 - device plane is optional
        pass
    try:
        from ray_tpu.native import counters as dark

        dark.publish()
    except Exception:  # noqa: BLE001 - counters are optional
        pass
    try:
        from ray_tpu.dag.channel import ring_stats
        from ray_tpu.util.metrics import sync_gauge

        fills = ring_stats()
        if fills:
            sync_gauge(
                "pipeline_ring_used_bytes",
                float(sum(r["used"] for r in fills)),
                "Bytes currently occupying this process's open shm rings.",
            )
            sync_gauge(
                "pipeline_ring_fill_max",
                float(max(r["fill"] for r in fills)),
                "Highest fill fraction across this process's open shm "
                "rings at the last observability tick.",
            )
    except Exception:  # noqa: BLE001 - toolchain missing
        pass


def hotpath_state() -> dict:
    """One self-describing snapshot of this PROCESS's execution-plane hot
    path: framing-path selection + counters, fused-event-loop occupancy
    and window sizes, open-ring fill levels, live pipeline stats, and the
    dispatch-overhead decomposition. Embedded by the agent's DebugState
    ``hotpath`` block and the head's ``QueryState("hotpath")``."""
    from ray_tpu.cluster import serialization as wire_mod
    from ray_tpu.util.metrics import _registry

    state = {
        "native_wire": wire_mod.NATIVE_WIRE,
        "wire": wire_mod.publish_wire_metrics(),
        "event_loops": loop_stats(),
    }
    try:
        from ray_tpu.native import counters as dark

        state["dark_counters"] = dark.publish()
    except Exception:  # noqa: BLE001 - counters are optional
        state["dark_counters"] = {}
    try:
        from ray_tpu.dag.channel import ring_stats

        state["rings"] = ring_stats()
    except Exception:  # noqa: BLE001 - toolchain missing
        state["rings"] = []
    try:
        from ray_tpu.dag.pipeline import pipeline_stats

        state["pipelines"] = pipeline_stats()
    except Exception:  # noqa: BLE001
        state["pipelines"] = []
    hist = _registry.get("dispatch_overhead_us")
    if hist is not None:
        state["dispatch_overhead_us"] = {
            stage: hist.summary({"stage": stage})
            for stage in ("serialize", "enqueue", "wire", "execute", "result")
        }
    return state


class FusedEventLoop:
    """Single-threaded ready-set/timer loop + bounded sender pool."""

    def __init__(self, name: str = "hotpath", senders: int = 8):
        self._name = name
        self._cv = threading.Condition()
        self._ready: List[Any] = []
        self._ready_set: set = set()
        # timers: authoritative map + lazy heap (stale heap entries are
        # skipped on pop) — O(log n) per re-arm instead of an O(n) scan
        # per wake on the one thread the submit plane serializes through
        self._deadlines: Dict[int, float] = {}  # id(src) -> deadline
        self._timer_heap: List[tuple] = []  # (deadline, id(src), src)
        self._sources: Dict[int, Any] = {}
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, senders), thread_name_prefix=f"{name}-send"
        )
        # stats (loop-thread-written, racily read)
        self._wakes = 0
        self._steps = 0
        self._offloads = 0
        self._busy_s = 0.0
        self._started_at = time.monotonic()
        self._batch_hist: List[int] = [0] * 12  # log2 batch-size buckets
        _LOOPS.add(self)

    def alive(self) -> bool:
        return not self._stop

    # -- registration --------------------------------------------------
    def register(self, source: Any) -> bool:
        """False = the loop is stopped (runtime shutdown): the caller
        must fail over itself — a silently unscheduled source would
        strand its queue forever."""
        with self._cv:
            if self._stop:
                return False
            self._sources[id(source)] = source
            self._ensure_thread_locked()
        self.wake(source)
        return True

    def unregister(self, source: Any) -> None:
        with self._cv:
            self._sources.pop(id(source), None)
            self._deadlines.pop(id(source), None)
            self._ready_set.discard(id(source))

    def _ensure_thread_locked(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name=f"{self._name}-loop", daemon=True
            )
            self._thread.start()

    # -- signalling ----------------------------------------------------
    def wake(self, source: Any) -> bool:
        """Mark ``source`` ready; one notify regardless of how much work
        was queued since its last step. False = not registered (loop
        stopped or source unregistered)."""
        with self._cv:
            if self._stop or id(source) not in self._sources:
                return False
            if id(source) not in self._ready_set:
                self._ready_set.add(id(source))
                self._ready.append(source)
                self._wakes += 1
                self._cv.notify()
            return True

    def offload(self, source: Any, fn: Callable, *args) -> bool:
        """Run a blocking action on the sender pool; wake ``source`` when
        it finishes (its step() observes completion and re-plans)."""

        def _run_action() -> None:
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 - actions own their errors
                logger.warning(
                    "hotpath action %r raised", fn, exc_info=True
                )
            finally:
                self.wake(source)

        self._offloads += 1
        try:
            self._pool.submit(_run_action)
            return True
        except RuntimeError:  # pool shut down under us (runtime exit)
            return False

    def note_batch(self, n: int) -> None:
        """Record a drained window size (log2-bucketed, lock-free)."""
        if n > 0:
            self._batch_hist[min(n.bit_length() - 1, 11)] += 1

    # -- loop ----------------------------------------------------------
    def _drop_stale_timers_locked(self) -> None:
        import heapq

        heap = self._timer_heap
        while heap and self._deadlines.get(heap[0][1]) != heap[0][0]:
            heapq.heappop(heap)  # re-armed or cancelled entry

    def _run(self) -> None:
        import heapq

        while True:
            with self._cv:
                while not self._ready and not self._stop:
                    self._drop_stale_timers_locked()
                    gap = 1.0
                    if self._timer_heap:
                        gap = self._timer_heap[0][0] - time.monotonic()
                        if gap <= 0.0:
                            break
                    self._cv.wait(timeout=gap)
                if self._stop:
                    return
                now = time.monotonic()
                batch = self._ready
                self._ready = []
                self._ready_set.clear()
                in_batch = {id(s) for s in batch}
                self._drop_stale_timers_locked()
                while self._timer_heap and self._timer_heap[0][0] <= now:
                    _, key, src = heapq.heappop(self._timer_heap)
                    self._deadlines.pop(key, None)
                    if key not in in_batch:
                        in_batch.add(key)
                        batch.append(src)
                    self._drop_stale_timers_locked()
            t0 = time.monotonic()
            for src in batch:
                with self._cv:
                    alive = id(src) in self._sources
                if not alive:
                    continue
                self._steps += 1
                try:
                    deadline = src.step(time.monotonic())
                except Exception:  # noqa: BLE001 - a source must not
                    # take the loop down; its own failure paths run on
                    # its next wake
                    logger.warning(
                        "hotpath source %r step raised", src, exc_info=True
                    )
                    deadline = time.monotonic() + 1.0
                with self._cv:
                    if id(src) in self._sources:
                        if deadline is not None:
                            self._deadlines[id(src)] = deadline
                            heapq.heappush(
                                self._timer_heap, (deadline, id(src), src)
                            )
                        else:
                            self._deadlines.pop(id(src), None)
            self._busy_s += time.monotonic() - t0

    def stats(self) -> dict:
        elapsed = max(1e-9, time.monotonic() - self._started_at)
        return {
            "name": self._name,
            "sources": len(self._sources),
            "wakes_total": self._wakes,
            "steps_total": self._steps,
            "offloads_total": self._offloads,
            "occupancy": round(self._busy_s / elapsed, 6),
            "busy_s": round(self._busy_s, 3),
            "batch_size_log2_hist": list(self._batch_hist),
        }

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._pool.shutdown(wait=False)
        t = self._thread
        if t is not None:
            t.join(timeout=3.0)
