"""Cross-node zero-copy transport: peer-leased worker<->worker data
sockets with C scatter-gather striping.

The same-node fast paths (shm arena views, ring pairs, the C wire plane)
stop at the node boundary; cross-node objects used to ride
agent-forwarded gRPC with per-chunk Python (~30x off the same-node shm
read). This module is the object_manager analog
(src/ray/object_manager/object_manager.h — direct node<->node object
transfer with the control plane OFF the data path):

- :class:`DataPlaneServer` runs beside each agent's RPC server and
  serves object stripes over raw TCP. Sends are scatter-gather straight
  from arena views (``native/net.cc`` ``sendmsg``; zero joins/copies
  send-side); the handshake is token-authenticated and epoch-fenced
  (stale-epoch senders rejected on the data path, mirroring
  FencedPayload on the control plane).
- :class:`PeerLink` is the owner-side half of a HEAD-GRANTED connection
  lease (GrantPeerLink — the task-lease pattern applied to transport):
  the head hands out ``endpoint + auth token`` once per (src, dst) pair,
  then steady-state transfers make ZERO head RPCs. Links cache pooled
  connections, renew while hot (piggybacked on agent reports), and are
  reclaimed on idle TTL / revoked on node death.
- :func:`fetch_to_store` / :func:`fetch_bytes` pull one object over a
  link: transfers larger than one stripe split across N parallel
  connections with per-stripe offsets; a severed connection re-fetches
  ONLY its lost stripes (resume, not restart), and in-flight bytes are
  capped for backpressure into the receiving arena. Payload lands via
  ``begin_put`` scatter-writes into the receiving arena (put_frames
  split into allocate / land / seal).

The chunked-RPC path (``object_plane.fetch_chunked``) stays as the
fallback for every failure class here, and ``RAY_TPU_NATIVE_NET=0``
kills the whole plane.
"""
from __future__ import annotations

import hmac
import logging
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.native.net import (
    NetClosedError,
    NetListener,
    NetSocket,
    NetTimeoutError,
    write_endpoint_file,
)

from .object_plane import (
    OBJECT_TRANSFER_BYTES,
    PEER_CONN_REUSED,
    TRANSFER_STRIPE_MS,
)

logger = logging.getLogger("ray_tpu.cluster.transport")

# handshake: magic | u16 version | u64 sender_epoch | u16 token_len |
#            u16 node_len | token | node_id
HELLO_MAGIC = b"RTN1"
_HELLO = struct.Struct("<4sHQHH")
_VERSION = 1
# handshake verdicts
HS_OK = 0
HS_BAD_TOKEN = 1
HS_STALE_EPOCH = 2
HS_MALFORMED = 3

# request: u8 op | u8 purpose | u16 oid_len | u64 offset | u64 length
_REQ = struct.Struct("<BBHQQ")
OP_FETCH = 1
_PURPOSES = ("get", "wait", "task_args")

# response: u8 status | u64 total_size | u64 payload_len
_RESP = struct.Struct("<BQQ")
ST_OK = 0
ST_MISSING = 1
ST_ERROR = 2


class LinkRejectedError(ConnectionError):
    """The serving agent refused the data-path handshake; the cached
    link is dead (drop it, fall back, re-grant on next use)."""

    def __init__(self, code: int, endpoint: str):
        self.code = code
        super().__init__(
            f"data-path handshake to {endpoint} rejected "
            f"({'bad token' if code == HS_BAD_TOKEN else 'stale epoch' if code == HS_STALE_EPOCH else code})"
        )


class StripeFetchError(ConnectionError):
    """A stripe could not be fetched within its retry budget — the
    caller falls back to the chunked-RPC path / its locate loop."""


def _stripe_cfg() -> Tuple[int, int, int]:
    """(stripe_bytes, max_conns, inflight_cap_bytes) from config."""
    from ray_tpu.config import cfg

    stripe = max(1 << 20, int(cfg.net_stripe_bytes))
    conns = max(1, int(cfg.net_stripe_conns))
    cap = max(stripe, int(cfg.net_inflight_cap_bytes))
    return stripe, conns, cap


class _FetchGate:
    """Process-wide in-flight byte budget across ALL concurrent socket
    fetches (cfg.net_fetch_inflight_cap_bytes) — the shuffle reduce
    side's arena backpressure: a task resolving many non-resident
    partitions at once parks its later pulls until earlier ones land
    (and, under arena pressure, until the spill path has drained the
    coldest residents), instead of staging an unbounded byte wave.

    Per-transfer stripe fan-out is separately capped by
    ``net_inflight_cap_bytes``; this gate composes across transfers.
    Advisory by construction: a transfer larger than the whole cap
    proceeds alone, and a waiter past its bounded deadline proceeds
    with the timeout counter bumped — backpressure must never become a
    deadlock. The park is additionally capped at ``MAX_PARK_S``: the
    acquire happens after the size handshake, when the SERVING side is
    already mid-send holding its admission slot (and its idle-close
    clock is ticking), so a parked fetch must release that remote
    pressure quickly rather than pin it for a whole caller deadline."""

    #: hard ceiling on one park (see class docstring) — well under the
    #: server's idle-close window so a park never severs the connection
    MAX_PARK_S = 15.0

    def __init__(self):
        self._cv = threading.Condition()
        self._inflight = 0
        self.waits = 0
        self.timeouts = 0

    def acquire(self, nbytes: int, timeout_s: float = MAX_PARK_S) -> int:
        from ray_tpu.config import cfg

        cap = int(cfg.net_fetch_inflight_cap_bytes)
        if cap <= 0 or nbytes <= 0:
            return 0
        timeout_s = min(timeout_s, self.MAX_PARK_S)
        deadline = time.monotonic() + max(0.05, timeout_s)
        with self._cv:
            waited = False
            while self._inflight > 0 and self._inflight + nbytes > cap:
                if not waited:
                    waited = True
                    self.waits += 1
                left = deadline - time.monotonic()
                if left <= 0:
                    self.timeouts += 1
                    break
                self._cv.wait(timeout=min(left, 1.0))
            self._inflight += nbytes
        return nbytes

    def release(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        with self._cv:
            self._inflight -= nbytes
            self._cv.notify_all()

    def snapshot(self) -> dict:
        with self._cv:
            return {
                "inflight_bytes": self._inflight,
                "waits": self.waits,
                "timeouts": self.timeouts,
            }


FETCH_GATE = _FetchGate()


# ---------------------------------------------------------------------------
# serving side
# ---------------------------------------------------------------------------


class DataPlaneServer:
    """Per-agent stripe server over raw TCP.

    One accept thread, one thread per live connection (connections are
    few by construction: peers x stripe conns, pooled and idle-reaped on
    the client side). Every payload send passes the agent's classed push
    admission, so socket transfers respect the same GET > WAIT >
    TASK_ARGS ordering as the RPC plane."""

    IDLE_CLOSE_S = 120.0  # server-side backstop on dead-silent conns

    def __init__(
        self,
        store,
        node_id: str,
        token: str,
        epoch_fn: Callable[[], Optional[int]],
        admission=None,
        host: str = "127.0.0.1",
    ):
        self.store = store
        self.node_id = node_id
        self._token = token.encode()
        self._epoch_fn = epoch_fn
        self._admission = admission
        self._listener = NetListener(host=host, port=0)
        self.endpoint = self._listener.address
        self._closed = False
        self._conns: Dict[int, NetSocket] = {}  # id(conn) -> conn (chaos)
        self._lock = threading.Lock()
        self.stats: Dict[str, int] = {
            "connections_accepted": 0,
            "handshakes_rejected_token": 0,
            "handshakes_rejected_epoch": 0,
            "stripes_served": 0,
            "bytes_sent": 0,
            "chaos_drops": 0,
        }
        # pid-stamped endpoint sidecar (swept at agent start when its
        # owner pid died — hygiene parity with arenas/rings)
        self._ep_file = write_endpoint_file(node_id, self.endpoint)
        threading.Thread(
            target=self._accept_loop,
            name=f"net-accept-{node_id[:6]}",
            daemon=True,
        ).start()

    # -- lifecycle -----------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn = self._listener.accept(timeout_s=1.0)
            except OSError:
                if self._closed:
                    return
                time.sleep(0.2)
                continue
            if conn is None:
                continue
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._conns[id(conn)] = conn
                self.stats["connections_accepted"] += 1
            threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                name=f"net-serve-{self.node_id[:6]}",
                daemon=True,
            ).start()

    def _drop_conn(self, conn: NetSocket) -> None:
        with self._lock:
            self._conns.pop(id(conn), None)
        conn.close()

    def chaos_drop(self) -> int:
        """Sever every live data connection (peer_conn_drop fault): the
        senders' in-flight stripes fail and must resume, not restart."""
        with self._lock:
            victims = list(self._conns.values())
            self._conns.clear()
            self.stats["chaos_drops"] += len(victims)
        for c in victims:
            c.close()
        return len(victims)

    def close(self) -> None:
        """Exactly-once teardown (idempotent like every close here)."""
        if self._closed:
            return
        self._closed = True
        self._listener.close()
        with self._lock:
            victims = list(self._conns.values())
            self._conns.clear()
        for c in victims:
            c.close()
        try:
            import os

            os.unlink(self._ep_file)
        except OSError:
            pass

    # -- protocol ------------------------------------------------------
    def _handshake(self, conn: NetSocket) -> bool:
        conn.set_timeout(10.0)
        try:
            hdr = conn.recv_exact(_HELLO.size)
            magic, version, epoch, tlen, nlen = _HELLO.unpack(hdr)
            if magic != HELLO_MAGIC or version != _VERSION:
                conn.send_vec([bytes([HS_MALFORMED])])
                return False
            token = conn.recv_exact(tlen)
            conn.recv_exact(nlen)  # sender node id (logging only)
            if not hmac.compare_digest(token, self._token):
                self.stats["handshakes_rejected_token"] += 1
                conn.send_vec([bytes([HS_BAD_TOKEN])])
                return False
            # epoch fence, FencedPayload semantics: only provably-stale
            # senders (stamped, and older than OUR adopted epoch) are
            # rejected; unstamped (0) passes — the sender re-registers
            # with the head and re-grants to resync
            ours = self._epoch_fn() or 0
            if epoch and ours and epoch < ours:
                self.stats["handshakes_rejected_epoch"] += 1
                conn.send_vec([bytes([HS_STALE_EPOCH])])
                return False
            conn.send_vec([bytes([HS_OK])])
            return True
        except (ConnectionError, TimeoutError, OSError):
            return False

    def _serve_conn(self, conn: NetSocket) -> None:
        try:
            if not self._handshake(conn):
                return
            conn.set_timeout(self.IDLE_CLOSE_S)
            while not self._closed:
                try:
                    req = conn.recv_exact(_REQ.size)
                except (NetTimeoutError, NetClosedError):
                    return  # idle backstop / client went away
                op, purpose_code, oid_len, offset, length = _REQ.unpack(req)
                oid = conn.recv_exact(oid_len).decode()
                if op != OP_FETCH:
                    return
                self._serve_stripe(
                    conn,
                    oid,
                    offset,
                    length,
                    _PURPOSES[purpose_code]
                    if purpose_code < len(_PURPOSES)
                    else "task_args",
                )
        except (ConnectionError, TimeoutError, OSError):
            pass  # severed mid-anything: the client resumes its stripes
        except Exception:  # noqa: BLE001 - serving must never kill the loop
            logger.exception("data-plane serve loop failed")
        finally:
            self._drop_conn(conn)

    def _serve_stripe(
        self, conn: NetSocket, oid: str, offset: int, length: int, purpose: str
    ) -> None:
        adm = self._admission(purpose) if self._admission is not None else None
        entered = False
        try:
            if adm is not None:
                adm.__enter__()
                entered = True
            try:
                total = self.store.object_size(oid)
            except KeyError:
                conn.send_vec([_RESP.pack(ST_MISSING, 0, 0)])
                return
            if offset >= total:
                conn.send_vec([_RESP.pack(ST_OK, total, 0)])
                return
            n = min(length, total - offset)
            sent = self._send_payload(conn, oid, offset, n, total)
            if sent:
                self.stats["stripes_served"] += 1
                self.stats["bytes_sent"] += n
                OBJECT_TRANSFER_BYTES.inc(n, labels={"path": "socket"})
        except KeyError:
            conn.send_vec([_RESP.pack(ST_MISSING, 0, 0)])
        except (ConnectionError, TimeoutError, OSError):
            raise
        except Exception:  # noqa: BLE001 - store-side failure
            logger.exception("stripe serve failed for %s", oid)
            try:
                conn.send_vec([_RESP.pack(ST_ERROR, 0, 0)])
            except (ConnectionError, TimeoutError, OSError):
                pass
        finally:
            # only a slot actually TAKEN is returned: __enter__ raising
            # (admission timeout) must not decrement the shared in-flight
            # count and silently widen the push cap
            if entered:
                adm.__exit__(None, None, None)

    def _send_payload(
        self, conn: NetSocket, oid: str, offset: int, n: int, total: int
    ) -> bool:
        """Header + payload in ONE gather send. Arena residents go out as
        a pinned read-only VIEW slice (zero copies between the shared
        pages and the socket); spilled / fallback-store objects pay one
        get_range copy."""
        hdr = _RESP.pack(ST_OK, total, n)
        inner = getattr(self.store, "inner", None)
        view = None
        if inner is not None and hasattr(inner, "get_view"):
            try:
                view = inner.get_view(oid)
            except (KeyError, BlockingIOError, OSError):
                view = None
        try:
            if view is not None and view.nbytes == total:
                conn.send_vec([hdr, view[offset : offset + n]])
                return True
        finally:
            # the slice sent synchronously; releasing the view pin now is
            # safe (sendmsg copied into the kernel before returning)
            del view
        data = self.store.get_range(oid, offset, n)
        if len(data) != n:
            conn.send_vec([_RESP.pack(ST_ERROR, 0, 0)])
            return False
        conn.send_vec([hdr, data])
        return True


# ---------------------------------------------------------------------------
# requesting side
# ---------------------------------------------------------------------------


class PeerLink:
    """Owner-side half of one head-granted peer connection lease.

    Pools established+handshaked connections per (src, dst) pair;
    ``borrow``/``give_back`` keep hot transfers dial-free, ``discard``
    drops a severed connection (the stripe that was riding it resumes on
    a fresh dial). ``last_used`` drives idle-TTL reclamation and the
    renew-while-hot piggyback."""

    def __init__(
        self,
        link_id: str,
        node_id: str,
        endpoint: str,
        token: str,
        epoch: Optional[int],
        src_node: str = "",
    ):
        self.link_id = link_id
        self.node_id = node_id
        self.endpoint = endpoint
        self.token = token
        self.epoch = epoch
        self.src_node = src_node
        self.last_used = time.monotonic()
        self._idle: List[NetSocket] = []
        self._lock = threading.Lock()
        self._closed = False
        self.transfers = 0

    def _dial(self, timeout_s: float = 10.0) -> NetSocket:
        host, port = self.endpoint.rsplit(":", 1)
        conn = NetSocket.connect(host, int(port), timeout_s=timeout_s)
        try:
            token = self.token.encode()
            src = self.src_node.encode()
            conn.send_vec(
                [
                    _HELLO.pack(
                        HELLO_MAGIC,
                        _VERSION,
                        int(self.epoch or 0),
                        len(token),
                        len(src),
                    ),
                    token,
                    src,
                ]
            )
            conn.set_timeout(timeout_s)
            verdict = conn.recv_exact(1)[0]
            if verdict != HS_OK:
                raise LinkRejectedError(verdict, self.endpoint)
            return conn
        except BaseException:
            conn.close()
            raise

    def borrow(self, timeout_s: float = 10.0) -> NetSocket:
        with self._lock:
            if self._closed:
                raise StripeFetchError(f"link to {self.node_id} is closed")
            if self._idle:
                return self._idle.pop()
        return self._dial(timeout_s)

    def give_back(self, conn: NetSocket) -> None:
        with self._lock:
            if not self._closed and not conn.closed and len(self._idle) < 8:
                self._idle.append(conn)
                return
        conn.close()

    def discard(self, conn: NetSocket) -> None:
        conn.close()

    def flush_idle(self) -> None:
        """Close every pooled connection. Called when one proves stale
        (a sever / server idle-reap usually killed the WHOLE pool): the
        next borrow dials fresh instead of popping more corpses."""
        with self._lock:
            victims = self._idle
            self._idle = []
        for c in victims:
            c.close()

    def touch(self) -> None:
        self.last_used = time.monotonic()
        self.transfers += 1

    def idle_for(self) -> float:
        return time.monotonic() - self.last_used

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            victims = self._idle
            self._idle = []
        for c in victims:
            c.close()


def _request(
    conn: NetSocket,
    oid: bytes,
    offset: int,
    length: int,
    purpose_code: int,
    timeout_s: float,
) -> Tuple[int, int]:
    """One stripe request/response header round-trip. Returns
    (total_size, payload_len); payload bytes are still on the wire for
    the caller to scatter-land."""
    conn.set_timeout(timeout_s)
    conn.send_vec(
        [_REQ.pack(OP_FETCH, purpose_code, len(oid), offset, length), oid]
    )
    status, total, plen = _RESP.unpack(conn.recv_exact(_RESP.size))
    if status == ST_MISSING:
        raise KeyError(oid.decode())
    if status != ST_OK:
        raise StripeFetchError(f"peer error serving {oid.decode()}")
    return total, plen


def _record_fetch_span(
    object_id: str, t_wall: float, total: int, stripes: int
) -> None:
    """Socket-plane trace span for one completed fetch (ISSUE 15):
    merged into the Chrome-trace export beside task slices."""
    try:
        from ray_tpu.util.tracing import SPANS

        SPANS.record(
            "socket_fetch",
            "transport",
            t_wall,
            time.time() - t_wall,
            object_id=object_id[:16],
            bytes=int(total),
            stripes=int(stripes),
        )
    except Exception:  # noqa: BLE001 - observability only
        pass


def _fetch(
    link: PeerLink,
    object_id: str,
    purpose: str,
    alloc: Callable[[int], memoryview],
    deadline: Optional[float] = None,
    on_stripe: Optional[Callable[[int, int], None]] = None,
) -> int:
    """Striped pull of one object over ``link`` into ``alloc(total)``.

    The first request doubles as the size handshake (no separate meta
    RPC): its reply carries total_size, the destination is allocated,
    and the first stripe lands straight into it. Remaining stripes fan
    out over up to net_stripe_conns parallel connections; each failed
    stripe resumes ALONE on a fresh connection (bounded retries), and a
    byte-capped semaphore backpressures the fan-out into the arena.

    ``on_stripe(off, n)`` fires after each stripe has FULLY landed in
    the destination (never for a partial recv — a severed stripe
    re-fetches before it is ever reported), so consumers like the
    device landing zone can overlap H2D with the remaining recv. It is
    called from the stripe worker threads and must be thread-safe.

    Raises KeyError (peer answered: object gone), LinkRejectedError
    (handshake refused: drop the cached link) or StripeFetchError
    (transport death past the retry budget) — every caller falls back
    to the chunked-RPC path on the latter two.
    """
    stripe_bytes, max_conns, cap_bytes = _stripe_cfg()
    purpose_code = (
        _PURPOSES.index(purpose) if purpose in _PURPOSES else 2
    )
    oid = object_id.encode()

    def _budget(cap: float = 60.0) -> float:
        if deadline is None:
            return cap
        left = deadline - time.monotonic()
        if left <= 0:
            raise StripeFetchError("stripe pull deadline")
        return min(cap, left)

    t0 = time.perf_counter()
    t_wall = time.time()
    # the probe tolerates ONE stale pooled connection (severed while
    # idle, or reaped by the server's idle backstop): retry on a fresh
    # dial before degrading the whole transfer to the RPC fallback.
    # alloc runs AT MOST ONCE (a staged arena entry must not double-
    # create on the retry) — the dest survives the reattempt.
    dest: Optional[memoryview] = None
    for probe_attempt in (0, 1):
        conn = link.borrow(timeout_s=_budget(10.0))
        try:
            total, plen = _request(
                conn, oid, 0, stripe_bytes, purpose_code, _budget()
            )
            if dest is None:
                dest = alloc(total)
            if plen:
                conn.recv_exact_into(dest[:plen])
                if on_stripe is not None:
                    on_stripe(0, plen)
            break
        except KeyError:
            link.give_back(conn)  # healthy connection, definite miss
            raise
        except (ConnectionError, TimeoutError, OSError) as exc:
            link.discard(conn)
            if probe_attempt or isinstance(exc, LinkRejectedError):
                raise
            # one stale pooled conn usually means the WHOLE pool is
            # stale (sever / idle-reap kills them together): flush it so
            # the retry — and the next transfers — dial fresh
            link.flush_idle()
        except BaseException:
            link.discard(conn)
            raise
    TRANSFER_STRIPE_MS.observe((time.perf_counter() - t0) * 1e3)
    link.touch()
    if plen >= total:
        link.give_back(conn)
        _record_fetch_span(object_id, t_wall, total, 1)
        return total

    # remaining stripes across parallel connections, resumable per stripe
    stripes = [
        (off, min(stripe_bytes, total - off))
        for off in range(plen, total, stripe_bytes)
    ]
    sem = threading.Semaphore(max(1, cap_bytes // stripe_bytes))
    q: List[Tuple[int, int]] = list(reversed(stripes))
    q_lock = threading.Lock()
    failures: List[BaseException] = []

    def _worker(seed_conn: Optional[NetSocket]) -> None:
        my_conn = seed_conn
        try:
            while True:
                with q_lock:
                    if failures or not q:
                        return
                    off, n = q.pop()
                if deadline is not None and time.monotonic() >= deadline:
                    raise StripeFetchError("stripe pull deadline")
                if not sem.acquire(timeout=max(0.05, _budget(120.0))):
                    raise StripeFetchError("stripe backpressure deadline")
                try:
                    my_conn = self_heal_fetch(off, n, my_conn)
                finally:
                    sem.release()
        except BaseException as exc:  # noqa: BLE001 - leader surfaces it
            with q_lock:
                failures.append(exc)
        finally:
            if my_conn is not None:
                link.give_back(my_conn)

    def self_heal_fetch(
        off: int, n: int, my_conn: Optional[NetSocket]
    ) -> Optional[NetSocket]:
        """One stripe with resume: a severed connection re-dials and
        re-requests ONLY this stripe (the landed bytes before the cut
        are overwritten in place — no duplicate-byte window)."""
        last: Optional[BaseException] = None
        for attempt in range(5):
            if attempt:
                # a chaos sever storm kills redials too: a short jittered
                # pause lets the window pass instead of burning the whole
                # budget inside one repeated cut
                time.sleep(0.02 * attempt)
            ts = time.perf_counter()
            try:
                if my_conn is None:
                    my_conn = link.borrow(timeout_s=_budget(10.0))
                _, got = _request(
                    my_conn, oid, off, n, purpose_code, _budget()
                )
                if got != n:
                    raise StripeFetchError(
                        f"stripe {off}: got {got} bytes, wanted {n}"
                    )
                my_conn.recv_exact_into(dest[off : off + n])
                if on_stripe is not None:
                    on_stripe(off, n)
                TRANSFER_STRIPE_MS.observe((time.perf_counter() - ts) * 1e3)
                return my_conn
            except (KeyError, LinkRejectedError):
                if my_conn is not None:
                    link.discard(my_conn)
                raise
            except (ConnectionError, TimeoutError, OSError) as exc:
                # severed / timed out mid-stripe: drop the connection and
                # resume THIS stripe on a fresh dial
                if my_conn is not None:
                    link.discard(my_conn)
                    my_conn = None
                last = exc
                try:
                    from ray_tpu.native import counters as _dark

                    _dark.add("net_stripe_retries_total")
                except Exception:  # noqa: BLE001 - counting is optional
                    pass
        raise StripeFetchError(
            f"stripe {off} of {object_id} failed after retries"
        ) from last

    n_workers = min(max_conns, len(stripes))
    threads = []
    for i in range(n_workers):
        # the probe connection seeds worker 0 (already dialed + hot)
        t = threading.Thread(
            target=_worker,
            args=(conn if i == 0 else None,),
            name="net-stripe",
            daemon=True,
        )
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    if failures:
        exc = failures[0]
        if isinstance(exc, (KeyError, LinkRejectedError)):
            raise exc
        raise StripeFetchError(
            f"striped pull of {object_id} failed: {exc!r}"
        ) from exc
    link.touch()
    _record_fetch_span(object_id, t_wall, total, 1 + len(stripes))
    return total


def _maybe_landing_zone(land: Optional[str], dest: memoryview):
    """A DeviceLandingZone over ``dest`` when ``land='device'`` asks for
    H2D/recv overlap AND the backend has a real H2D hop to hide (see
    device_plane.landing_zone_worthwhile); None otherwise."""
    if land != "device":
        return None
    from ray_tpu.cluster import device_plane

    if not device_plane.landing_zone_worthwhile():
        return None
    return device_plane.DeviceLandingZone(dest)


def fetch_bytes(
    link: PeerLink,
    object_id: str,
    purpose: str = "task_args",
    deadline: Optional[float] = None,
    land: Optional[str] = None,
) -> bytearray:
    """Pull one object over the link into host memory (driver-side /
    arena-less callers). ``land='device'`` additionally streams landed
    stripes to the device in flight (device landing zone) so the
    deserialize-time ``device_put`` of device frames reads warm pages —
    a no-op on host-aliasing backends where no H2D hop exists."""
    out: List[bytearray] = []
    gated = [0]
    zone: List[object] = [None]

    def alloc(total: int) -> memoryview:
        gated[0] = FETCH_GATE.acquire(
            total,
            _FetchGate.MAX_PARK_S
            if deadline is None
            else max(0.05, deadline - time.monotonic()),
        )
        buf = bytearray(total)
        out.append(buf)
        mv = memoryview(buf)
        zone[0] = _maybe_landing_zone(land, mv)
        return mv

    def on_stripe(off: int, n: int) -> None:
        z = zone[0]
        if z is not None:
            z.note_stripe(off, n)

    try:
        _fetch(link, object_id, purpose, alloc, deadline, on_stripe)
        if zone[0] is not None:
            zone[0].finish()
    except BaseException:
        if zone[0] is not None:
            zone[0].abort()
        raise
    finally:
        FETCH_GATE.release(gated[0])
    return out[0]


def fetch_to_store(
    link: PeerLink,
    object_id: str,
    store,
    purpose: str = "task_args",
    deadline: Optional[float] = None,
    land: Optional[str] = None,
) -> int:
    """Pull one object over the link and land it in the local store.

    Zero-copy landing: stripes scatter-write into an UNSEALED arena
    entry (``store.begin_put``) and the object seals only after the last
    stripe — readers can never observe a half-landed object, and an
    aborted transfer frees its staged pages. When the arena cannot host
    the object even after eviction, stripes land in host memory and the
    joined bytes take ``put_bytes`` (which owns the spill fallback).

    ``land='device'`` wraps the staged entry in a device landing zone:
    completed stripes of the contiguous prefix are ``device_put`` in
    flight so the consumer's deserialize-time H2D overlaps the recv. An
    abort frees BOTH sides — partial device buffers (zone.abort) and
    staged arena pages (abort_put) — and per-stripe resume is
    unaffected because the zone only ever consumes fully-landed
    disjoint stripes. Returns the object's size."""
    state: Dict[str, object] = {}
    gated = [0]
    zone: List[object] = [None]

    def alloc(total: int) -> memoryview:
        # cross-fetch byte gate BEFORE staging arena pages: concurrent
        # partition pulls queue here while earlier ones land/spill
        gated[0] = FETCH_GATE.acquire(
            total,
            _FetchGate.MAX_PARK_S
            if deadline is None
            else max(0.05, deadline - time.monotonic()),
        )
        staged = None
        beginner = getattr(store, "begin_put", None)
        if beginner is not None:
            try:
                staged = beginner(object_id, total)
            except KeyError:
                # already stored locally (raced another pull): land into
                # throwaway host memory; commit becomes a no-op
                state["dup"] = True
                staged = None
            except Exception:  # noqa: BLE001 - arena unavailable
                staged = None
        if staged is None:
            buf = bytearray(total)
            state["buf"] = buf
            staged = memoryview(buf)
        else:
            state["staged"] = True
        zone[0] = _maybe_landing_zone(land, staged)
        return staged

    def on_stripe(off: int, n: int) -> None:
        z = zone[0]
        if z is not None:
            z.note_stripe(off, n)

    try:
        total = _fetch(link, object_id, purpose, alloc, deadline, on_stripe)
        if zone[0] is not None:
            zone[0].finish()
    except BaseException:
        if zone[0] is not None:
            zone[0].abort()
        if state.get("staged"):
            store.abort_put(object_id)
        raise
    finally:
        FETCH_GATE.release(gated[0])
    if state.get("dup"):
        return total
    if state.get("staged"):
        store.commit_put(object_id)
    else:
        store.put_bytes(object_id, bytes(state["buf"]))
    return total


# ---------------------------------------------------------------------------
# link cache (per requesting process)
# ---------------------------------------------------------------------------


class PeerLinkCache:
    """Granted links by destination node, with idle-TTL reclamation.

    ``get`` returns a cached link (bumping ``peer_conn_reused_total`` —
    the zero-head-RPC steady state) or grants through the provided
    ``grant_fn`` once. ``sweep_idle`` closes and returns links whose
    last transfer is older than the idle TTL; ``hot_links`` lists ids to
    renew on the next piggybacked report."""

    def __init__(self, grant_fn: Callable[[str], Optional[PeerLink]]):
        self._grant = grant_fn
        self._links: Dict[str, PeerLink] = {}
        self._lock = threading.Lock()

    def get(self, node_id: str) -> Optional[PeerLink]:
        with self._lock:
            link = self._links.get(node_id)
        if link is not None:
            PEER_CONN_REUSED.inc()
            return link
        link = self._grant(node_id)
        if link is None:
            return None
        with self._lock:
            cur = self._links.setdefault(node_id, link)
        if cur is not link:
            link.close()
        return cur

    def drop(self, node_id: str, link_id: Optional[str] = None) -> bool:
        """Invalidate a cached link (revocation, handshake rejection,
        node death). ``link_id`` guards against dropping a REPLACEMENT
        grant that raced in."""
        with self._lock:
            link = self._links.get(node_id)
            if link is None or (
                link_id is not None and link.link_id != link_id
            ):
                return False
            del self._links[node_id]
        link.close()
        return True

    def hot_links(self, horizon_s: float) -> List[str]:
        with self._lock:
            return [
                l.link_id
                for l in self._links.values()
                if l.idle_for() <= horizon_s
            ]

    def sweep_idle(self, idle_ttl_s: float) -> List[PeerLink]:
        with self._lock:
            victims = [
                (nid, l)
                for nid, l in self._links.items()
                if l.idle_for() > idle_ttl_s
            ]
            for nid, _ in victims:
                del self._links[nid]
        for _, l in victims:
            l.close()
        return [l for _, l in victims]

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [
                {
                    "link_id": l.link_id,
                    "node_id": nid,
                    "endpoint": l.endpoint,
                    "idle_s": round(l.idle_for(), 1),
                    "transfers": l.transfers,
                }
                for nid, l in self._links.items()
            ]

    def close(self) -> None:
        with self._lock:
            victims = list(self._links.values())
            self._links.clear()
        for l in victims:
            l.close()
