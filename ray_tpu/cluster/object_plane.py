"""Object-plane instruments + the chunked peer-pull client.

One home for the data-path metrics every process family shares
(``object_transfer_bytes_total{path=shm|inline|rpc}``, shm hit/miss
counters, chunk latency) and for ``fetch_chunked`` — the streamed,
resumable replacement for the single-shot ``FetchObject`` reply
(object_manager chunked pushes, push_manager.h:28-36: bounded in-flight
chunks, per-chunk retry, so one dropped chunk re-sends itself instead of
the whole object, and a big broadcast never holds one giant buffer per
receiver in flight).
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from ray_tpu.util.metrics import Counter as _Counter
from ray_tpu.util.metrics import Gauge as _Gauge
from ray_tpu.util.metrics import Histogram as _Histogram

OBJECT_TRANSFER_BYTES = _Counter(
    "object_transfer_bytes_total",
    "Object payload bytes moved, by path: shm (zero-copy arena view), "
    "inline (control-message inline value), rpc (pickled fetch / chunked "
    "peer pull), socket (direct peer-leased data socket, scatter-gather "
    "C plane).",
    label_names=("path",),
)
PEER_CONN_GRANTED = _Counter(
    "peer_conn_granted_total",
    "Peer data-link leases granted by the head (one per (src, dst) pair "
    "until revoked/returned; steady-state transfers reuse the grant).",
)
PEER_CONN_REVOKED = _Counter(
    "peer_conn_revoked_total",
    "Peer data-link leases revoked (node death, renewal expiry) or "
    "returned on idle TTL.",
)
PEER_CONN_REUSED = _Counter(
    "peer_conn_reused_total",
    "Transfers served from an already-granted cached peer link (zero "
    "head RPCs).",
)
TRANSFER_STRIPE_MS = _Histogram(
    "transfer_stripe_ms",
    "Per-stripe round-trip latency of socket peer transfers (request "
    "sent to last payload byte landed).",
    boundaries=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 5000),
)
SHM_HITS = _Counter(
    "shm_store_hits_total",
    "Object reads served as zero-copy views over the local shm arena.",
)
SHM_MISSES = _Counter(
    "shm_store_misses_total",
    "Object reads that missed the local arena and fell back to an RPC.",
)
TRANSFER_CHUNK_MS = _Histogram(
    "transfer_chunk_ms",
    "Per-chunk round-trip latency of chunked peer object pulls.",
    boundaries=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 5000),
)
CHUNKED_PULLS_INFLIGHT = _Gauge(
    "chunked_pulls_inflight",
    "Chunked peer pulls currently in progress in this process.",
)


class ChunkFetchError(Exception):
    """A chunk could not be fetched within its retry budget (the caller
    falls over to the next replica / the locate loop)."""


def fetch_chunked(
    client,
    object_id: str,
    purpose: str = "task_args",
    size: Optional[int] = None,
    deadline: Optional[float] = None,
    relocate=None,
) -> "bytes | bytearray":
    """Pull one object from a peer agent, chunked and resumable.

    Small objects (<= cfg.transfer_chunk_bytes) take the single-shot
    ``FetchObject`` path. Larger ones stream ``FetchObjectChunk`` windows
    with at most cfg.transfer_max_inflight_chunks concurrent requests;
    each chunk retries independently (transport retries + one re-request)
    before the whole pull is abandoned with :class:`ChunkFetchError`.

    ``relocate`` (optional, ``() -> client | None``) is consulted between
    chunk retry attempts after a TRANSPORT failure: it re-resolves the
    object's location and returns the client to continue from (the same
    peer, or a replica the directory moved to). ``None`` means the source
    is gone everywhere it was known — the pull aborts IMMEDIATELY with
    :class:`ChunkFetchError` so the caller re-plans through its locate
    loop instead of burning the whole per-chunk retry budget against a
    dead peer.

    Raises ``KeyError`` when the peer no longer holds the object.
    """
    from ray_tpu.config import cfg

    def _remaining(cap: float) -> float:
        """Per-attempt RPC budget bounded by the caller's deadline."""
        if deadline is None:
            return cap
        left = deadline - time.monotonic()
        if left <= 0:
            raise TimeoutError("chunked pull deadline")
        return min(cap, left)

    chunk_bytes = max(64 * 1024, int(cfg.transfer_chunk_bytes))
    if size is None:
        size = client.call(
            "FetchObjectMeta",
            {"object_id": object_id},
            timeout=_remaining(15.0),
        )["size"]
    if size <= chunk_bytes:
        # transfer bytes are counted once per wire crossing, at the
        # SERVING agent's handler — counting here too would double every
        # peer transfer in aggregated views
        t0 = time.perf_counter()
        data = client.call(
            "FetchObject",
            {"object_id": object_id, "purpose": purpose},
            timeout=_remaining(60.0),
        )
        TRANSFER_CHUNK_MS.observe((time.perf_counter() - t0) * 1e3)
        return data

    offsets = list(range(0, size, chunk_bytes))
    buf = bytearray(size)
    max_inflight = max(1, int(cfg.transfer_max_inflight_chunks))
    sem = threading.Semaphore(max_inflight)
    failed: list = []
    fail_lock = threading.Lock()
    # current source peer, shared across chunk threads: a mid-transfer
    # relocation swaps the client for EVERY remaining chunk at once
    peer = [client]

    def _relocate_peer() -> None:
        """One thread re-resolves the source after a transport failure;
        a gone-everywhere verdict aborts the pull (caller re-plans)."""
        if relocate is None:
            return
        with fail_lock:
            cur = peer[0]
        try:
            fresh = relocate()
        except Exception:  # noqa: BLE001 - locate failed: keep retrying
            return
        if fresh is None:
            raise ChunkFetchError(
                f"source of {object_id} is gone (re-resolve found no "
                "live replica); caller must re-plan"
            )
        if fresh is not cur:
            with fail_lock:
                peer[0] = fresh

    def _one(off: int) -> None:
        want = min(chunk_bytes, size - off)
        try:
            # per-chunk resume: transport retries inside call(), plus one
            # full re-request here — a chaos-dropped chunk re-sends alone.
            # every attempt's timeout shrinks to the caller's remaining
            # deadline (a 2s-budget pull must not park for 3 x 60s)
            for attempt in (0, 1, 2):
                t0 = time.perf_counter()
                with fail_lock:
                    cur = peer[0]
                try:
                    part = cur.call(
                        "FetchObjectChunk",
                        {
                            "object_id": object_id,
                            "offset": off,
                            "length": want,
                            "purpose": purpose,
                        },
                        timeout=_remaining(60.0),
                        retries=1,
                    )
                except (KeyError, TimeoutError):
                    raise
                except Exception:  # noqa: BLE001 - dropped/slow chunk
                    if attempt == 2:
                        raise
                    # re-resolve the location BEFORE the retry: a dead
                    # source must not eat the remaining budget too
                    _relocate_peer()
                    continue
                TRANSFER_CHUNK_MS.observe((time.perf_counter() - t0) * 1e3)
                if len(part) != want:
                    raise ChunkFetchError(
                        f"chunk {off} of {object_id}: got {len(part)} "
                        f"bytes, wanted {want}"
                    )
                buf[off : off + want] = part
                return
        except BaseException as exc:  # noqa: BLE001 - surfaced by leader
            with fail_lock:
                failed.append(exc)
        finally:
            sem.release()

    CHUNKED_PULLS_INFLIGHT.inc()
    try:
        threads = []
        for off in offsets:
            with fail_lock:
                if failed:
                    break
            if deadline is not None and time.monotonic() >= deadline:
                failed.append(TimeoutError("chunked pull deadline"))
                break
            # a bounded slot wait: every in-flight chunk's RPC timeout is
            # deadline-capped, so a slot frees within the budget or the
            # pull is over anyway
            if deadline is None:
                sem.acquire()
            elif not sem.acquire(
                timeout=max(0.05, deadline - time.monotonic())
            ):
                failed.append(TimeoutError("chunked pull deadline"))
                break
            t = threading.Thread(
                target=_one, args=(off,), name="chunk-pull", daemon=True
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if failed:
            exc = failed[0]
            if isinstance(exc, KeyError):
                raise exc
            raise ChunkFetchError(
                f"chunked pull of {object_id} failed: {exc!r}"
            ) from exc
        # (bytes counted once at the serving agent's chunk handler)
        # hand back the assembled buffer itself: a bytes() of it would
        # double peak memory per pull, and every consumer (store puts,
        # inline replies, pickle loads) takes any bytes-like
        return buf
    finally:
        CHUNKED_PULLS_INFLIGHT.dec()
