"""Leader-side control-plane replication: WAL shipping to warm standbys.

The head's persistence stream (WAL records + debounced snapshots,
persistence.py) doubles as a replication stream: every durable record
gets a monotonically increasing sequence number and lands in a bounded
in-memory ring; a shipper thread pushes ``ReplWal`` batches to every
registered :class:`~ray_tpu.cluster.standby.StandbyHead` over the
ordinary RPC layer. Snapshots enter the ring as seq-stamped barriers —
captured while the persist lock is held, so a barrier can never be
ordered ahead of a record it does not contain (records racing the
capture double-apply, which is idempotent; nothing is ever lost).

Gap handling is the standby's ``resync_from`` reply: the shipper rewinds
to the requested seq when the ring still holds it, or ships a fresh
snapshot + tail when it fell off (``wal_ship_resyncs_total``). Shipping
is asynchronous by default; ``RAY_TPU_WAL_SHIP_ACKED=1`` makes the WAL
flush wait (bounded) for standby acks.

A standby that answers ``{"fenced": epoch}`` has promoted: the hub
routes that into the head's step-down path — the deposed leader fences
itself off its own shipping stream, no external coordinator needed.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.config import cfg
from ray_tpu.util.metrics import Counter as _Counter
from ray_tpu.util.metrics import Gauge as _Gauge
from ray_tpu.util.metrics import Histogram as _Histogram

logger = logging.getLogger("ray_tpu.cluster.replication")

# 1 for this process's current head role, 0 for the others — one gauge,
# role as label, so dashboards see transitions (leader -> fenced) as a
# flip, not a new series
HEAD_ROLE = _Gauge(
    "head_role",
    "1 for this head process's current role (leader|standby|fenced).",
    label_names=("role",),
)
WAL_SHIPPED = _Counter(
    "wal_shipped_total",
    "WAL records (and snapshot barriers) acked by standbys.",
)
WAL_SHIP_LAG = _Gauge(
    "wal_ship_lag_records",
    "Largest standby replication lag in records (leader seq - ack).",
)
WAL_SHIP_RESYNCS = _Counter(
    "wal_ship_resyncs_total",
    "Standby re-syncs (gap past the ring -> fresh snapshot shipped).",
)
FAILOVER_MS = _Histogram(
    "failover_ms",
    "Standby promotion latency: leader-declared-dead to the promoted "
    "head's listener bound and serving.",
    boundaries=(10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 15000),
)

_ROLES = ("leader", "standby", "fenced")


def set_role(role: str) -> None:
    for r in _ROLES:
        HEAD_ROLE.set(1.0 if r == role else 0.0, labels={"role": r})


# a standby unreachable for this many consecutive ship attempts is
# dropped from the registry (it re-hellos when it returns); generous —
# a dropped standby silently stops replicating
_STANDBY_MAX_STRIKES = 8


class ReplicationHub:
    """Sequenced replication ring + standby registry + shipper thread.

    ``publish``/``publish_snapshot`` are called with the head's persist
    lock held — that lock is what serializes seq assignment with the
    on-disk WAL/snapshot order. The shipper thread takes only this hub's
    own lock, so acked waits can never deadlock against it.
    """

    def __init__(self, head):
        self._head = head
        self._cv = threading.Condition()
        self.seq = 0
        # (seq, ("wal", record) | ("snap", snapshot_dict))
        self._ring: deque = deque()
        self._standbys: Dict[str, dict] = {}
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

    # -- stream production (leader, under the persist lock) -------------
    def publish(self, records) -> int:
        """Append WAL records to the replication stream; returns the last
        assigned seq (0 when replication is inert)."""
        if not records:
            return 0
        with self._cv:
            if self._stopped:
                return 0
            # with no standby registered the ring retains nothing (a
            # late joiner bootstraps from a fresh snapshot at the
            # current seq); seq still advances so positions stay valid
            retain = bool(self._standbys)
            for rec in records:
                self.seq += 1
                if retain:
                    self._ring.append((self.seq, ("wal", rec)))
            self._trim_locked()
            last = self.seq
            if retain:
                self._cv.notify_all()
        return last

    def publish_snapshot(self, snap: dict) -> int:
        """A snapshot barrier: the standby resets its tables from it.
        Must be called while the caller still holds the persist lock the
        snapshot was captured under (see module docstring ordering
        argument)."""
        with self._cv:
            if self._stopped:
                return 0
            self.seq += 1
            if self._standbys:
                self._ring.append((self.seq, ("snap", snap)))
                self._trim_locked()
                self._cv.notify_all()
            return self.seq

    def _trim_locked(self) -> None:
        cap = max(64, int(cfg.wal_ship_ring))
        while len(self._ring) > cap:
            self._ring.popleft()

    # -- standby registry -----------------------------------------------
    def register_standby(
        self, standby_id: str, address: str, from_seq: int
    ) -> None:
        with self._cv:
            if self._stopped:
                return
            old = self._standbys.get(standby_id)
            if old is not None and old.get("client") is not None:
                try:
                    old["client"].close()
                except Exception:  # noqa: BLE001
                    pass
            self._standbys[standby_id] = {
                "address": address,
                "acked": int(from_seq),
                "strikes": 0,
                "client": None,
                "last_sent": time.monotonic(),
            }
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._ship_loop,
                    name="head-wal-shipper",
                    daemon=True,
                )
                self._thread.start()
            self._cv.notify_all()
        logger.info(
            "standby %s registered at %s (from seq %d)",
            standby_id[:8],
            address,
            from_seq,
        )

    def wait_acked(self, seq: int, timeout: float) -> bool:
        """Acked shipping: block until every live standby applied
        ``seq`` (or none are registered / the timeout passes)."""
        deadline = time.monotonic() + max(0.0, timeout)
        with self._cv:
            while not self._stopped:
                live = [
                    e
                    for e in self._standbys.values()
                    if e["strikes"] < _STANDBY_MAX_STRIKES
                ]
                if not live or all(e["acked"] >= seq for e in live):
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(min(remaining, 0.1))
        return False

    # -- shipping --------------------------------------------------------
    def _ship_loop(self) -> None:
        while True:
            with self._cv:
                if self._stopped:
                    return
                pending = any(
                    e["acked"] < self.seq for e in self._standbys.values()
                )
                if not self._standbys or not pending:
                    self._cv.wait(timeout=0.2)
                    if self._stopped:
                        return
                targets = list(self._standbys.keys())
            struck_before = self._total_strikes()
            for sid in targets:
                try:
                    self._ship_to(sid)
                except Exception:  # noqa: BLE001 - one standby, one strike
                    logger.exception("WAL ship to standby %s failed", sid[:8])
                    self._strike(sid)
            self._keepalives()
            self._update_lag()
            if self._total_strikes() > struck_before:
                # an unreachable standby must accrue strikes on a real
                # clock, not at connect-refused speed — a sub-second
                # blip would otherwise burn the whole strike budget
                time.sleep(0.25)

    def _total_strikes(self) -> int:
        with self._cv:
            return sum(e["strikes"] for e in self._standbys.values())

    def _keepalives(self) -> None:
        """An up-to-date standby still needs to hear from the leader:
        the shipped stream is its liveness signal (and a standby the
        leader silently dropped notices the silence and re-hellos)."""
        from .common import WalShipBatch
        from .rpc import RpcError

        now = time.monotonic()
        with self._cv:
            due = [
                (sid, e["acked"])
                for sid, e in self._standbys.items()
                if e["acked"] >= self.seq
                and now - e.get("last_sent", 0.0) > 1.0
            ]
        for sid, acked in due:
            client = self._client_for(sid)
            if client is None:
                continue
            try:
                reply = client.call(
                    "ReplWal",
                    WalShipBatch(
                        epoch=self._head.cluster_epoch,
                        leader=self._head.address,
                        start_seq=acked + 1,
                    ),
                    timeout=5.0,
                )
            except RpcError:
                self._strike(sid)
                continue
            if isinstance(reply, dict) and "fenced" in reply:
                self._head._step_down(
                    int(reply["fenced"]),
                    "standby promoted over us",
                    leader_hint=reply.get("leader", ""),
                )
                return
            with self._cv:
                e = self._standbys.get(sid)
                if e is not None:
                    e["last_sent"] = now
                    e["strikes"] = 0

    def _client_for(self, sid: str):
        from .rpc import RpcClient

        with self._cv:
            e = self._standbys.get(sid)
            if e is None:
                return None
            if e["client"] is None:
                e["client"] = RpcClient(e["address"])
            return e["client"]

    def _ship_to(self, sid: str) -> None:
        from .rpc import RpcError

        while True:
            with self._cv:
                e = self._standbys.get(sid)
                if e is None or self._stopped:
                    return
                acked = e["acked"]
                if acked >= self.seq:
                    return
                ring_start = self._ring[0][0] if self._ring else self.seq + 1
                behind_ring = acked + 1 < ring_start
                batch_cap = max(1, int(cfg.wal_ship_batch))
                items = (
                    []
                    if behind_ring
                    else [
                        (s, item)
                        for s, item in self._ring
                        if s > acked
                    ][:batch_cap]
                )
            if behind_ring:
                self._resync(sid)
                return
            if not items:
                return
            from .common import WalShipBatch

            payload = WalShipBatch(
                epoch=self._head.cluster_epoch,
                leader=self._head.address,
                start_seq=items[0][0],
                records=[item for _, item in items],
            )
            client = self._client_for(sid)
            if client is None:
                return
            try:
                reply = client.call("ReplWal", payload, timeout=10.0)
            except RpcError:
                self._strike(sid)
                return
            if not isinstance(reply, dict):
                self._strike(sid)
                return
            if "fenced" in reply:
                # the standby promoted: this leader is deposed — fence
                # ourselves off our own shipping stream
                self._head._step_down(
                    int(reply["fenced"]),
                    "standby promoted over us",
                    leader_hint=reply.get("leader", ""),
                )
                return
            if "resync_from" in reply:
                want = int(reply["resync_from"])
                WAL_SHIP_RESYNCS.inc()
                with self._cv:
                    e = self._standbys.get(sid)
                    if e is not None:
                        e["acked"] = want - 1
                        e["strikes"] = 0
                continue  # retry immediately from the rewound position
            applied = int(reply.get("applied_to", acked))
            shipped = 0
            with self._cv:
                e = self._standbys.get(sid)
                if e is not None:
                    shipped = max(0, applied - e["acked"])
                    e["acked"] = max(e["acked"], applied)
                    e["strikes"] = 0
                    e["last_sent"] = time.monotonic()
                    self._cv.notify_all()
            if shipped:
                WAL_SHIPPED.inc(shipped)
            else:
                # no progress (e.g. the standby is mid-promotion and
                # neither applies nor fences): back off to the outer
                # loop's cadence instead of re-sending in a tight spin
                return

    def _resync(self, sid: str) -> None:
        """The standby's position fell off the ring: ship a fresh
        snapshot (captured now, seq read first so the overlap
        double-applies instead of losing records) plus nothing — the
        tail records ship normally on the next pass."""
        from .rpc import RpcError

        WAL_SHIP_RESYNCS.inc()
        from .common import WalShipBatch

        from_seq = self.seq
        snap = self._head._snapshot_state()
        payload = WalShipBatch(
            epoch=self._head.cluster_epoch,
            leader=self._head.address,
            start_seq=from_seq + 1,
            snapshot=snap,
            snap_seq=from_seq,
        )
        client = self._client_for(sid)
        if client is None:
            return
        try:
            reply = client.call("ReplWal", payload, timeout=30.0)
        except RpcError:
            self._strike(sid)
            return
        if isinstance(reply, dict) and "fenced" in reply:
            self._head._step_down(
                int(reply["fenced"]),
                "standby promoted over us",
                leader_hint=reply.get("leader", ""),
            )
            return
        with self._cv:
            e = self._standbys.get(sid)
            if e is not None:
                e["acked"] = max(e["acked"], from_seq)
                e["strikes"] = 0
                self._cv.notify_all()

    def _strike(self, sid: str) -> None:
        with self._cv:
            e = self._standbys.get(sid)
            if e is None:
                return
            e["strikes"] += 1
            if e["strikes"] >= _STANDBY_MAX_STRIKES:
                logger.warning(
                    "standby %s unreachable for %d ship attempts; "
                    "dropping (it re-registers via StandbyHello)",
                    sid[:8],
                    e["strikes"],
                )
                dead = self._standbys.pop(sid)
                if dead.get("client") is not None:
                    try:
                        dead["client"].close()
                    except Exception:  # noqa: BLE001
                        pass
            self._cv.notify_all()

    def _update_lag(self) -> None:
        with self._cv:
            lags = [
                self.seq - e["acked"] for e in self._standbys.values()
            ]
        WAL_SHIP_LAG.set(float(max(lags) if lags else 0))

    # -- lifecycle / observability --------------------------------------
    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            standbys = list(self._standbys.values())
            self._standbys.clear()
            self._cv.notify_all()
        for e in standbys:
            if e.get("client") is not None:
                try:
                    e["client"].close()
                except Exception:  # noqa: BLE001
                    pass

    def state(self) -> dict:
        with self._cv:
            return {
                "seq": self.seq,
                "ring_records": len(self._ring),
                "standbys": [
                    {
                        "standby_id": sid,
                        "address": e["address"],
                        "acked_seq": e["acked"],
                        "lag_records": self.seq - e["acked"],
                        "strikes": e["strikes"],
                    }
                    for sid, e in self._standbys.items()
                ],
            }
