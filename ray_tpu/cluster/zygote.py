"""Fork-server ("zygote") for millisecond worker spawn.

The agent's cold spawn path pays a full interpreter start + the worker
module graph import (grpc, cloudpickle — and jax when ``JAX_PLATFORMS``
is set) per worker: seconds on a loaded host, and the dominant cost of
actor churn (BENCH_r05 actor_creations_per_s). The reference avoids it
with worker_pool.cc's prestarted idle workers; CPython can do one
better: ONE process (this module) pays the import exactly once, then
``os.fork()`` clones it per worker in milliseconds.

Design constraints that keep fork safe:

- The zygote is single-threaded: a line-oriented stdin/stdout protocol,
  no RPC server, no grpc channels, no event loops. grpc and jax are
  only *imported* here — neither creates core threads or backends until
  first object/backend use, which happens post-fork in the child.
- Children reset SIGCHLD, detach from the protocol pipes (stdout is
  re-pointed at stderr so a printing worker can never corrupt a reply),
  then run the exact same ``worker.run_worker`` entry as a cold spawn.
- ``ray_tpu._ids`` registers an ``os.register_at_fork`` hook, so forked
  workers never mint ids from an inherited entropy buffer.

Lifecycle chaining: the zygote exits on stdin EOF (its agent died), and
forked workers exit when ``os.getppid() == 1`` (their zygote died) —
the same orphan checks the cold path relies on, one level deeper.

Protocol (one JSON object per line):

    agent -> zygote   {"cmd": "fork", "worker_id": ..., "env": {...}}
                      {"cmd": "reap"}
    zygote -> agent   {"pid": 12345, "exited": [...]} | {"error": "..."}
                      {"exited": [...]}

Every reply carries the pids the zygote reaped since the last reply:
pids recycle once reaped, so ``os.kill(pid, 0)`` alone could see a dead
worker as alive forever (and a later SIGKILL could hit an innocent
process). ``ForkedProc.poll`` consults the client's reaped-set first;
the agent's report loop calls ``drain_exits()`` each sweep to keep it
fresh.

The agent-side ``ZygoteClient`` lives here too so the whole fork-server
surface is one file.
"""
from __future__ import annotations

import json
import os
import select
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

_READY_LINE = b'{"ready": true}\n'


# ---------------------------------------------------------------------------
# agent side
# ---------------------------------------------------------------------------
class ForkedProc:
    """Popen-shaped handle for a worker forked by the zygote (the child
    belongs to the zygote, so ``waitpid`` is unavailable here). Liveness:
    the owning client's reaped-exit set is authoritative (immune to pid
    reuse); signal 0 covers the window before the next protocol reply."""

    def __init__(self, pid: int, owner: Optional["ZygoteClient"] = None):
        self.pid = pid
        self.returncode: Optional[int] = None
        self._owner = owner

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        if self._owner is not None and self.pid in self._owner.exited:
            self.returncode = -9
            return self.returncode
        try:
            os.kill(self.pid, 0)
            return None
        except OSError:
            self.returncode = -9
            return self.returncode

    def _signal(self, sig: int) -> None:
        if self.returncode is not None:
            return
        os.kill(self.pid, sig)

    def kill(self) -> None:
        import signal

        self._signal(signal.SIGKILL)

    def terminate(self) -> None:
        import signal

        self._signal(signal.SIGTERM)


def fork_available() -> bool:
    return hasattr(os, "fork") and sys.platform != "win32"


class ZygoteClient:
    """Agent-side handle to one zygote process.

    ``fork_worker`` is the only hot call: serialized under one lock
    (forks are ms-scale), returns a ``ForkedProc`` or ``None`` on ANY
    failure — the caller falls back to cold spawn. A client that broke
    stays broken (the agent may start a replacement)."""

    def __init__(self, agent_address: str, store_path: str, env: Dict[str, str]):
        self._lock = threading.Lock()
        self._buf = b""
        self.broken = False
        self._ready = False
        # pids the zygote reaped — the pid-reuse-proof death signal
        # ForkedProc.poll consults (set ops are GIL-atomic)
        self.exited: set = set()
        self.proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu.cluster.zygote",
                "--agent",
                agent_address,
                "--store",
                store_path,
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            bufsize=0,
            env=env,
        )

    def _read_line(self, deadline: float) -> Optional[bytes]:
        """One protocol line from the zygote, or None on timeout/EOF."""
        fd = self.proc.stdout.fileno()
        while b"\n" not in self._buf:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            r, _, _ = select.select([fd], [], [], min(remaining, 0.25))
            if not r:
                if self.proc.poll() is not None:
                    return None
                continue
            chunk = os.read(fd, 4096)
            if not chunk:  # EOF: zygote died
                return None
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return line

    def _wait_ready(self, deadline: float) -> bool:
        if self._ready:
            return True
        line = self._read_line(deadline)
        if line is None or json.loads(line).get("ready") is not True:
            return False
        self._ready = True
        return True

    def fork_worker(
        self,
        worker_id: str,
        env: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
    ) -> Optional[ForkedProc]:
        if timeout is None:
            from ray_tpu.config import cfg

            timeout = cfg.zygote_ready_timeout_s
        with self._lock:
            if self.broken:
                return None
            deadline = time.monotonic() + timeout
            try:
                if not self._wait_ready(deadline):
                    self.broken = True
                    return None
                req = {"cmd": "fork", "worker_id": worker_id, "env": env or {}}
                self.proc.stdin.write(json.dumps(req).encode() + b"\n")
                self.proc.stdin.flush()
                line = self._read_line(deadline)
                if line is None:
                    self.broken = True
                    return None
                reply = json.loads(line)
                self.exited.update(reply.get("exited") or ())
                pid = reply.get("pid")
                if pid is None:
                    self.broken = True
                    return None
                return ForkedProc(int(pid), owner=self)
            except (OSError, ValueError):
                self.broken = True
                return None

    def drain_exits(self) -> set:
        """Pull reaped-child pids from the zygote (pid-reuse-proof death
        detection for forked workers). NEVER blocks on the client lock:
        the agent's report loop calls this ahead of its NodeReport, and a
        fork_worker holding the lock through the zygote's import warmup
        must not stall heartbeats into a false node death. No-op while
        the zygote is warming; any protocol failure marks it broken."""
        if not self._lock.acquire(blocking=False):
            return self.exited  # a fork is in flight; catch up next tick
        try:
            if self.broken or self.proc.poll() is not None:
                return self.exited
            if not self._ready and not self._wait_ready(
                time.monotonic() + 0.01
            ):
                return self.exited  # still importing; nothing forked yet
            try:
                self.proc.stdin.write(b'{"cmd": "reap"}\n')
                self.proc.stdin.flush()
                line = self._read_line(time.monotonic() + 5.0)
                if line is None:
                    self.broken = True
                    return self.exited
                self.exited.update(json.loads(line).get("exited") or ())
            except (OSError, ValueError):
                self.broken = True
            return self.exited
        finally:
            self._lock.release()

    def close(self) -> None:
        with self._lock:
            self.broken = True
            try:
                self.proc.stdin.close()
            except OSError:
                pass
            try:
                self.proc.terminate()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# zygote process side
# ---------------------------------------------------------------------------
_EXITED: list = []  # reaped child pids, drained into protocol replies


def _reap(_sig=None, _frm=None) -> None:
    """Collect exited forked workers and record their pids: reaping frees
    the pid for reuse, so the AGENT must learn the death through the
    protocol, not through signal-0 probes."""
    try:
        while True:
            pid, _ = os.waitpid(-1, os.WNOHANG)
            if pid == 0:
                break
            _EXITED.append(pid)  # list.append is signal/GIL safe
    except ChildProcessError:
        pass


def _child_main(agent_address: str, store_path: str, req: dict) -> None:
    """Runs in the forked child: detach from the zygote's protocol pipes,
    apply per-worker env, become a normal worker process."""
    import signal

    signal.signal(signal.SIGCHLD, signal.SIG_DFL)
    devnull = os.open(os.devnull, os.O_RDWR)
    os.dup2(devnull, 0)
    os.dup2(2, 1)  # user prints must never corrupt the reply pipe
    os.close(devnull)
    for k, v in (req.get("env") or {}).items():
        os.environ[k] = str(v)
    from . import worker as worker_mod

    worker_mod.run_worker(agent_address, req["worker_id"], store_path)


def main() -> None:
    import argparse
    import signal

    parser = argparse.ArgumentParser(description="ray_tpu worker fork-server")
    parser.add_argument("--agent", required=True)
    parser.add_argument("--store", default="")
    args = parser.parse_args()

    # Pay the worker's import graph ONCE, pre-fork. Mirrors worker.main:
    # jax is imported (and its platform pinned) only when JAX_PLATFORMS
    # is set — config.update creates no backend, so no threads exist at
    # fork time. RAY_TPU_ZYGOTE_PRELOAD names extra modules to warm.
    from . import worker as _worker_mod  # noqa: F401 - import for side effect

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax

            jax.config.update("jax_platforms", plat)
        except Exception:  # noqa: BLE001 - jax optional
            pass
    for name in filter(None, os.environ.get("RAY_TPU_ZYGOTE_PRELOAD", "").split(",")):
        try:
            __import__(name.strip())
        except Exception:  # noqa: BLE001 - best-effort warmup
            pass

    signal.signal(signal.SIGCHLD, _reap)
    out = sys.stdout.buffer
    out.write(_READY_LINE)
    out.flush()

    def reply(obj: dict) -> None:
        n = len(_EXITED)
        obj["exited"], _EXITED[:n] = _EXITED[:n], []
        try:
            out.write(json.dumps(obj).encode() + b"\n")
            out.flush()
        except OSError:  # agent closed the pipe mid-reply (shutdown race)
            sys.exit(0)

    while True:
        line = sys.stdin.readline()
        if not line:  # EOF: the agent died; forked workers follow via ppid
            return
        try:
            req = json.loads(line)
        except ValueError:
            continue
        cmd = req.get("cmd")
        if cmd == "exit":
            return
        if cmd == "reap":
            reply({})
            continue
        if cmd != "fork":
            reply({"error": "unknown cmd"})
            continue
        try:
            pid = os.fork()
        except OSError as exc:
            reply({"error": repr(exc)})
            continue
        if pid == 0:
            try:
                _child_main(args.agent, args.store, req)
            finally:
                os._exit(1)
        reply({"pid": pid})


if __name__ == "__main__":
    main()
