"""Single-file web dashboard (reference: python/ray/dashboard/client/).

The reference ships a built React frontend; here one self-contained HTML
page (no external assets — the cluster may have zero egress) polls the
dashboard's JSON APIs and renders live node / actor / placement-group /
job tables plus RPC handler timings. Served at ``/ui``.
"""

UI_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  :root { color-scheme: light dark; }
  body { font-family: system-ui, sans-serif; margin: 0; padding: 0 1.2rem 2rem;
         background: Canvas; color: CanvasText; }
  h1 { font-size: 1.15rem; margin: 0.9rem 0 0.2rem; }
  h1 small { font-weight: normal; opacity: 0.65; font-size: 0.75rem; }
  h2 { font-size: 0.95rem; margin: 1.1rem 0 0.3rem; }
  table { border-collapse: collapse; width: 100%; font-size: 0.8rem; }
  th, td { text-align: left; padding: 0.22rem 0.55rem;
           border-bottom: 1px solid color-mix(in srgb, CanvasText 15%, Canvas); }
  th { opacity: 0.65; font-weight: 600; }
  .pill { display: inline-block; border-radius: 0.6rem; padding: 0 0.45rem;
          font-size: 0.72rem; }
  .ok { background: #1a7f3722; color: #1a7f37; }
  .bad { background: #d1242f22; color: #d1242f; }
  .mut { opacity: 0.6; }
  #summary { display: flex; gap: 1.6rem; flex-wrap: wrap; margin: 0.5rem 0; }
  #summary div { font-size: 0.8rem; }
  #summary b { display: block; font-size: 1.15rem; }
  #err { color: #d1242f; font-size: 0.8rem; }
</style>
</head>
<body>
<h1>ray_tpu cluster <small id="addr"></small></h1>
<div id="err"></div>
<div id="summary"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Placement groups</h2><table id="pgs"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>RPC handlers (head)</h2><table id="rpc"></table>
<script>
const esc = (s) => String(s ?? "").replace(/[&<>]/g,
  (c) => ({"&": "&amp;", "<": "&lt;", ">": "&gt;"}[c]));
const pill = (ok, txt) =>
  `<span class="pill ${ok ? "ok" : "bad"}">${esc(txt)}</span>`;
function table(el, header, rows) {
  document.getElementById(el).innerHTML =
    "<tr>" + header.map((h) => `<th>${esc(h)}</th>`).join("") + "</tr>" +
    (rows.length
      ? rows.map((r) => "<tr>" + r.map((c) => `<td>${c}</td>`).join("") +
          "</tr>").join("")
      : `<tr><td class="mut" colspan="${header.length}">none</td></tr>`);
}
async function j(path) { const r = await fetch(path); return r.json(); }
async function tick() {
  try {
    const [status, nodes, actors, pgs, jobs, rpc] = await Promise.all([
      j("/api/cluster_status"), j("/api/nodes"), j("/api/actors"),
      j("/api/placement_groups"), j("/api/jobs"), j("/api/rpc_stats"),
    ]);
    document.getElementById("err").textContent = "";
    document.getElementById("addr").textContent = status.head_address || "";
    const s = status.leases || {};
    document.getElementById("summary").innerHTML = [
      ["nodes", (nodes || []).filter((n) => n.Alive).length +
        " / " + (nodes || []).length],
      ["actors", (actors || []).length],
      ["placement groups", (pgs || []).length],
      ["jobs", (jobs || []).length],
      ["pending leases", (s.pending ?? 0) + (s.infeasible ?? 0)],
      ["in flight", s.in_flight ?? 0],
    ].map(([k, v]) => `<div><b>${esc(v)}</b>${esc(k)}</div>`).join("");
    table("nodes", ["node", "alive", "address", "resources"],
      (nodes || []).map((n) => [
        esc((n.NodeID || "").slice(0, 12)), pill(n.Alive, n.Alive ? "alive" : "dead"),
        esc(n.Address || n.address || ""),
        esc(JSON.stringify(n.Resources || n.resources || {})),
      ]));
    table("actors", ["actor", "name", "class", "state", "node", "restarts"],
      (actors || []).map((a) => [
        esc((a.actor_id || "").slice(0, 12)), esc(a.name || ""),
        esc(a.class_name || ""), pill(a.state === "ALIVE", a.state),
        esc((a.node_id || "").slice(0, 12)), esc(a.num_restarts ?? 0),
      ]));
    table("pgs", ["pg", "strategy", "state", "bundles"],
      (pgs || []).map((p) => [
        esc((p.pg_id || p.id || "").slice(0, 12)), esc(p.strategy || ""),
        pill(p.state === "CREATED" || p.ready, p.state || (p.ready ? "ready" : "pending")),
        esc(JSON.stringify(p.bundles || [])),
      ]));
    table("jobs", ["job", "status", "entrypoint"],
      (jobs || []).map((jb) => [
        esc(jb.job_id || ""), pill(jb.status === "SUCCEEDED" ||
          jb.status === "RUNNING", jb.status || ""),
        esc(jb.entrypoint || ""),
      ]));
    const handlers = Object.entries(rpc.head || rpc || {})
      .sort((a, b) => (b[1].count || 0) - (a[1].count || 0)).slice(0, 20);
    table("rpc", ["handler", "calls", "mean ms", "max ms"],
      handlers.map(([name, h]) => [
        esc(name), esc(h.count ?? ""),
        esc(h.mean_ms != null ? h.mean_ms.toFixed(2) : ""),
        esc(h.max_ms != null ? h.max_ms.toFixed(2) : ""),
      ]));
  } catch (e) {
    document.getElementById("err").textContent = "refresh failed: " + e;
  }
}
tick();
setInterval(tick, 2000);
</script>
</body>
</html>
"""
