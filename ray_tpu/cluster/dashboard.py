"""Dashboard: HTTP observability + REST job API on the head.

The lightweight analog of the reference dashboard head
(/root/reference/python/ray/dashboard/head.py, aiohttp) and its job REST
module (dashboard/modules/job/): JSON state endpoints, a Prometheus
text exposition endpoint (the metrics-agent scrape surface,
_private/metrics_agent.py), and job submit/list/logs over HTTP.
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional

from aiohttp import web


class Dashboard:
    def __init__(self, head, host: str = "127.0.0.1", port: int = 0):
        self.head = head
        self.host = host
        self._port = port
        self.port: Optional[int] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self.error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._serve, name="dashboard", daemon=True
        )
        self._thread.start()
        self._started.wait(timeout=10)
        if self.error is not None:
            raise RuntimeError(
                f"dashboard failed to start on {host}:{port}"
            ) from self.error
        if self.port is None:
            raise RuntimeError("dashboard did not start within 10s")

    # ------------------------------------------------------------------
    def _routes(self) -> web.Application:
        app = web.Application()
        app.add_routes(
            [
                web.get("/", self._index),
                web.get("/ui", self._ui),
                web.get("/api/cluster_status", self._cluster_status),
                web.get("/api/nodes", self._nodes),
                web.get("/api/nodes/{node_id}/debug", self._node_debug),
                web.get("/api/rpc_stats", self._rpc_stats),
                web.get("/api/actors", self._actors),
                web.get("/api/objects", self._objects),
                web.get("/api/placement_groups", self._pgs),
                web.get("/api/jobs", self._jobs),
                web.post("/api/jobs", self._submit_job),
                web.get("/api/jobs/{job_id}", self._job_status),
                web.get("/api/jobs/{job_id}/logs", self._job_logs),
                web.get("/metrics", self._metrics),
            ]
        )
        return app

    def _serve(self) -> None:
        try:
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            runner = web.AppRunner(self._routes())
            self._loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.host, self._port)
            self._loop.run_until_complete(site.start())
            self.port = site._server.sockets[0].getsockname()[1]
        except Exception as exc:  # noqa: BLE001 - surfaced to the constructor
            self.error = exc
            self._started.set()
            return
        self._started.set()
        self._loop.run_forever()
        self._loop.run_until_complete(runner.cleanup())

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)

    # ------------------------------------------------------------------
    def _json(self, data) -> web.Response:
        return web.json_response(data, dumps=lambda d: json.dumps(d, default=str))

    async def _ui(self, request) -> web.Response:
        """Self-contained live dashboard page (dashboard/client analog —
        one HTML file polling the JSON APIs; no external assets)."""
        from .dashboard_ui import UI_HTML

        return web.Response(text=UI_HTML, content_type="text/html")

    async def _index(self, request) -> web.Response:
        info = self.head._h_query_state({"kind": "summary"})
        html = (
            "<html><head><title>ray_tpu dashboard</title></head><body>"
            "<h1>ray_tpu cluster</h1>"
            f"<pre>{json.dumps(info, indent=2, default=str)}</pre>"
            "<p>endpoints: /api/cluster_status /api/nodes "
            "/api/nodes/&lt;id&gt;/debug /api/rpc_stats /api/actors "
            "/api/objects /api/placement_groups /api/jobs /metrics</p>"
            "</body></html>"
        )
        return web.Response(text=html, content_type="text/html")

    async def _cluster_status(self, request) -> web.Response:
        info = self.head._h_cluster_info(None)
        info["head_address"] = self.head.address
        info["leases"] = self.head._h_query_state({"kind": "leases"})
        return self._json(info)

    async def _node_debug(self, request) -> web.Response:
        """Proxy one agent's DebugState (node_manager DebugString analog):
        ledger availability, store stats, OOM kills, in-flight queues,
        per-RPC-handler timings."""
        node_id = request.match_info["node_id"]
        client = self.head._clients.get(node_id)
        if client is None:
            return self._json({"error": f"unknown node {node_id}"})
        loop = asyncio.get_running_loop()
        try:
            state = await loop.run_in_executor(
                None, lambda: client.call("DebugState", timeout=10.0)
            )
            return self._json(state)
        except Exception as exc:  # noqa: BLE001
            return self._json({"error": repr(exc)})

    async def _rpc_stats(self, request) -> web.Response:
        """The head's own per-handler timings (instrumented_io_context
        stats analog)."""
        from .rpc import HANDLER_STATS

        return self._json(HANDLER_STATS.snapshot())

    async def _nodes(self, request) -> web.Response:
        return self._json(self.head._h_cluster_info(None)["nodes"])

    async def _actors(self, request) -> web.Response:
        return self._json(self.head._h_query_state({"kind": "actors"}))

    async def _objects(self, request) -> web.Response:
        return self._json(self.head._h_query_state({"kind": "objects"}))

    async def _pgs(self, request) -> web.Response:
        return self._json(self.head._h_query_state({"kind": "placement_groups"}))

    async def _jobs(self, request) -> web.Response:
        return self._json(self.head.jobs.list())

    async def _submit_job(self, request) -> web.Response:
        body = await request.json()
        job_id = self.head.jobs.submit(
            entrypoint=body["entrypoint"],
            runtime_env=body.get("runtime_env"),
            submission_id=body.get("submission_id"),
            metadata=body.get("metadata"),
        )
        return self._json({"job_id": job_id})

    async def _job_status(self, request) -> web.Response:
        try:
            return self._json(self.head.jobs.status(request.match_info["job_id"]))
        except ValueError:
            raise web.HTTPNotFound()

    async def _job_logs(self, request) -> web.Response:
        try:
            logs = self.head.jobs.logs(request.match_info["job_id"])
        except ValueError:
            raise web.HTTPNotFound()
        return web.Response(text=logs, content_type="text/plain")

    async def _metrics(self, request) -> web.Response:
        """Prometheus text exposition: the head's federated registry —
        typed HELP/TYPE, histograms with buckets, every sample labeled
        node/role, agents' and workers' shipped deltas included. (The
        old handler hand-rolled ~a dozen head counters as ``# TYPE ...
        counter`` lines, mislabeling gauges and dropping every
        histogram.) Rendering does one cluster-info pass plus a registry
        walk — off the event loop like the node-debug proxy."""
        loop = asyncio.get_running_loop()
        body = await loop.run_in_executor(None, self.head.metrics_text)
        return web.Response(
            text=body, content_type="text/plain"
        )
